"""Full-parameter sharding (ZeRO-3 / FSDP): the shard layout and the
gather-on-use / reduce-scatter-into-shard collectives.

The ZeRO optimizers (:mod:`apex_tpu.contrib.optimizers.distributed`)
shard the *optimizer state* over the data axis but keep a replicated
copy of every parameter on every device — which is exactly what caps
the flagship at h≈1024 on 16 GB HBM (PROFILE_r05.md: MFU 0.55+ is an
h≥4096 property, and the replicated layout cannot hold that model).
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (PAPERS.md, arXiv 2004.13336) is the TPU design this module
implements: parameters live *permanently* as 1-D fp32 shards, are
all-gathered to model dtype **per bucket on use**, and gradients
reduce-scatter straight into the shard — no replicated master, no
full-size gradient buffer, no tail all-gather.

Layout (:class:`Zero3Layout`): the param pytree is bucketed by the PR 4
:class:`~apex_tpu.parallel.overlap.GradientBuckets` plan — size-
targeted, single-dtype buckets in REVERSE tree order, so the
*first-used* buckets (embeddings, early layers) sit at the END of the
shard and their gathers are issued last, closest to their consumers
(prefetch-friendly under a latency-hiding scheduler).  Each bucket is
padded to the shard axis extent and split; the per-device shard is the
fp32 concatenation of the per-bucket chunks.  That flat shard IS the
fp32 master: the sharded optimizer update runs on it in place (one
contiguous single-dtype buffer — the PR 7 fused-tail memory pattern
for free), and LAMB's per-parameter trust ratios survive via the same
segment-id machinery as the state-sharded path.

Collectives:

- :meth:`Zero3Layout.gather` — per-bucket all-gather of the params on
  use.  The fp32 chunk is cast to the bucket's MODEL dtype before the
  gather (bf16 params move half the bytes; cast-then-gather equals
  gather-then-cast bit for bit), or — with
  ``CompressionConfig(ici_legs=True)`` — quantized to int8 + per-block
  fp32 scales (:func:`~apex_tpu.ops.quantization.quantized_all_gather`,
  ~4× fewer bytes on the wire), with an optional per-bucket ``ag``
  error-feedback residual.  Each bucket's gather is wrapped in the
  ``tlm.param_gather`` phase and reported to the telemetry stream as a
  ``param_gather`` event with ring-model wire-byte estimates.
- :meth:`Zero3Layout.reduce_scatter_grads` — per-bucket RS(ici) →
  AR(dcn) of the gradients, landing each device exactly its shard's
  elements (the hierarchical legs and their int8 variants are the PR 7
  chunk-preserving ones, so compression never moves a shard boundary).
  There is no grad all-gather: the reduced chunk feeds the sharded
  update directly.

Memory model (why this unlocks h≥4096): replicated DDP holds, per
device, the model-dtype params + fp32 master + two fp32 moments ≈
14–16 bytes/param *persistently*.  Under ZeRO-3 the persistent
footprint is (4 + 8)/world bytes/param (fp32 shard + moments), and the
full-width weights exist only transiently while the step uses them —
bounded by the model-dtype param bytes, with per-bucket gathers giving
the scheduler independently-placeable live ranges instead of one
monolithic materialization.  ``tools/memory_audit.py`` proves the
per-device bytes from the compiled program's ``memory_analysis()``.

Everything here must be called inside ``shard_map`` (or ``pmap``) with
the axes bound, except the host-side constructor/`unshard` paths which
take a ``mesh``.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.parallel.overlap import (
    DEFAULT_BUCKET_BYTES,
    GradientBuckets,
    _local_shape,
)
from apex_tpu.telemetry import events as _events

__all__ = ["Zero3Layout", "zero3_comm_state", "zero3_comm_specs"]


def _axis_size(axis_name) -> int:
    from apex_tpu._compat import axis_size

    return int(axis_size(axis_name))


def _split_axes(axis_name) -> Tuple[Optional[str], str]:
    """(dcn_axis_or_None, shard_axis) from a flat name or (dcn, ici)."""
    if isinstance(axis_name, (tuple, list)):
        return axis_name[0], axis_name[1]
    return None, axis_name


class Zero3Layout:
    """The deterministic shard layout for one param pytree.

    A pure function of (local leaf shapes, model dtypes, bucket_bytes,
    world) — the same determinism contract as
    :class:`~apex_tpu.parallel.overlap.GradientBuckets`, which is what
    lets the host-side construction (``param_specs``/``mesh`` for
    model-sharded leaves) and the trace-time one inside ``shard_map``
    agree, so shard/state placement can be computed outside the
    compiled step.
    """

    def __init__(self, params_like: Any, world: int,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 param_specs: Any = None, mesh=None):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        leaves, treedef = jax.tree_util.tree_flatten(params_like)
        if param_specs is not None:
            specs = treedef.flatten_up_to(param_specs)
        else:
            specs = [None] * len(leaves)
        self.treedef = treedef
        self.shapes = [
            tuple(_local_shape(l, s, mesh))
            for l, s in zip(leaves, specs)
        ]
        # canonicalized like the bucket plan's: a numpy float64
        # template must describe the float32 the traced step sees
        self.dtypes = [
            jax.dtypes.canonicalize_dtype(l.dtype)
            if hasattr(l, "dtype")
            else jnp.asarray(l).dtype for l in leaves
        ]
        self.world = int(world)
        # model-dtype buckets (dtype=None): single-dtype by assembly, so
        # the uncompressed gather can move model-dtype bytes; for_tree
        # already derives LOCAL shapes under param_specs/mesh, matching
        # what shard_map will see
        self.plan = GradientBuckets.for_tree(
            params_like, bucket_bytes, param_specs=param_specs,
            mesh=mesh,
        )
        self.padded = [
            b.size + (-b.size) % self.world for b in self.plan.buckets
        ]
        self.chunk_sizes = [p // self.world for p in self.padded]
        self.offsets = list(np.cumsum([0] + self.chunk_sizes[:-1]))
        self.shard_size = int(sum(self.chunk_sizes))
        self.num_leaves = len(leaves)

    # ------------------------------------------------------------ host
    @property
    def names(self) -> List[str]:
        return self.plan.names

    def segment_ids(self) -> np.ndarray:
        """Flat shard-layout index → leaf id (host constant); bucket
        padding gets the extra id ``num_leaves`` so it never
        contaminates a real parameter (the LAMB trust-ratio contract
        of ``_FlatMeta.segment_ids``, in bucket order).  Built from
        the ONE per-bucket id construction (:meth:`_bucket_id_vectors`)
        so it can never diverge from the per-rank slices."""
        parts = self._bucket_id_vectors()
        return (np.concatenate([np.asarray(v) for v in parts])
                if parts else np.zeros((0,), np.int32))

    def local_segment_ids(self, rank) -> jnp.ndarray:
        """This rank's ``(shard_size,)`` slice of :meth:`segment_ids`
        (``rank`` may be a traced ``lax.axis_index``)."""
        # per-bucket dynamic_slice of the bucket's own id vector: the
        # shard concatenates per-bucket chunks, so one global slice
        # would pick the wrong elements
        parts = []
        full = self._bucket_id_vectors()
        for i, chunk in enumerate(self.chunk_sizes):
            parts.append(jax.lax.dynamic_slice(
                full[i], (rank * chunk,), (chunk,)
            ))
        return (jnp.concatenate(parts) if parts
                else jnp.zeros((0,), jnp.int32))

    def _bucket_id_vectors(self) -> List[jnp.ndarray]:
        out = []
        for b, padded in zip(self.plan.buckets, self.padded):
            ids = np.concatenate(
                [np.full((s,), i, np.int32)
                 for i, s in zip(b.leaf_ids, b.sizes)]
                if b.leaf_ids else [np.zeros((0,), np.int32)]
            )
            ids = np.concatenate([
                ids,
                np.full((padded - b.size,), self.num_leaves, np.int32),
            ])
            out.append(jnp.asarray(ids))
        return out

    def unshard(self, global_shards: np.ndarray) -> Any:
        """Host-side: rebuild the full replicated param pytree from the
        ``device_get`` of the sharded flat buffer (global shape
        ``(world * shard_size,)``, rank-major — the shape a
        ``P(shard_axis)``-placed shard array materializes to).  The
        inverse of ``shard_params``+time: use it to resume a ZeRO-3
        checkpoint into a replicated-eval setup; values are the exact
        fp32 masters cast to model dtype — bit-identical to a
        FULL-WIDTH :meth:`gather` (under int8 gathers the on-device
        view is the lossy wire format; this rebuild is the exact
        source of truth, i.e. at least as accurate)."""
        flat = np.asarray(global_shards).reshape(-1)
        expect = self.world * self.shard_size
        if flat.size != expect:
            raise ValueError(
                f"global shards have {flat.size} elements, the layout "
                f"expects world({self.world}) x shard({self.shard_size})"
                f" = {expect}: was the checkpoint written at a "
                "different world size or bucket_bytes?"
            )
        per_rank = flat.reshape(self.world, self.shard_size)
        out: List[Any] = [None] * self.num_leaves
        for i, b in enumerate(self.plan.buckets):
            off, chunk = self.offsets[i], self.chunk_sizes[i]
            full = np.concatenate(
                [per_rank[r, off:off + chunk] for r in range(self.world)]
            )[: b.size]
            pos = 0
            for leaf_id, size in zip(b.leaf_ids, b.sizes):
                out[leaf_id] = full[pos:pos + size].reshape(
                    self.shapes[leaf_id]
                ).astype(self.dtypes[leaf_id])
                pos += size
        return jax.tree_util.tree_unflatten(self.treedef, out)

    # ------------------------------------------------- inside shard_map
    def shard_params(self, params: Any, rank) -> jnp.ndarray:
        """This rank's permanent ``(shard_size,)`` fp32 shard of a
        (replicated) param pytree — call once at init inside
        ``shard_map`` (``rank = lax.axis_index(shard_axis)``)."""
        leaves = self.treedef.flatten_up_to(params)
        bufs = self.plan.pack(leaves)  # model-dtype flat buffers
        parts = []
        for i, (buf, padded) in enumerate(zip(bufs, self.padded)):
            buf = buf.astype(jnp.float32)
            if padded != buf.size:
                buf = jnp.concatenate(
                    [buf, jnp.zeros((padded - buf.size,), jnp.float32)]
                )
            chunk = self.chunk_sizes[i]
            parts.append(jax.lax.dynamic_slice(
                buf, (rank * chunk,), (chunk,)
            ))
        return (jnp.concatenate(parts) if parts
                else jnp.zeros((0,), jnp.float32))

    def bucket_chunk(self, shard: jnp.ndarray, i: int) -> jnp.ndarray:
        """Bucket *i*'s slice of the flat shard."""
        return shard[self.offsets[i]: self.offsets[i]
                     + self.chunk_sizes[i]]

    def _unpack_bucket(self, i: int, full: jnp.ndarray) -> List[Any]:
        """Bucket *i*'s gathered (padded) flat buffer → its leaves in
        model shape/dtype; returns [(leaf_id, leaf), ...]."""
        b = self.plan.buckets[i]
        out = []
        pos = 0
        for leaf_id, size in zip(b.leaf_ids, b.sizes):
            out.append((leaf_id, full[pos:pos + size].reshape(
                self.shapes[leaf_id]).astype(self.dtypes[leaf_id])))
            pos += size
        return out

    def gather(
        self,
        shard: jnp.ndarray,
        axis_name: Any,
        compression: Any = None,
        residuals: Optional[dict] = None,
        step=None,
    ) -> Tuple[Any, Optional[dict]]:
        """Gather-on-use: per-bucket all-gather of the full weights in
        model dtype.  ``axis_name`` is the flat shard axis or the
        hierarchical ``(dcn, ici)`` pair (the gather rides the ici leg
        only — shards are replicated across dcn, so no parameter bytes
        ever cross the slow axis).  With ``compression.ici_legs`` the
        AG payload is int8 + per-block fp32 scales
        (:func:`~apex_tpu.ops.quantization.quantized_all_gather`), with
        a per-bucket ``ag`` error-feedback residual when ``residuals``
        is given.  Returns ``(params, new_residuals_or_None)``;
        ``new_residuals`` echoes the untouched grad-leg residuals so
        the caller can thread one state dict."""
        from apex_tpu.ops.quantization import as_compression_config
        from apex_tpu.telemetry.spans import phase as _phase

        cfg = as_compression_config(compression)
        _, shard_axis = _split_axes(axis_name)
        quantize = cfg is not None and cfg.ici_legs
        use_ef = (quantize and cfg is not None and cfg.error_feedback
                  and residuals is not None)
        self._emit_gather_events(axis_name, cfg)
        out: List[Any] = [None] * self.num_leaves
        new_residuals: Optional[dict] = (
            {k: dict(v) for k, v in residuals.items()}
            if residuals is not None else None
        )
        base_key = None
        if (quantize and cfg.rounding == "stochastic"
                and step is not None):
            # leg 2 of the PR 7 per-leg decorrelation scheme (0 = dcn,
            # 1 = grad RS), then per bucket — re-deriving the grad
            # legs' keys here would re-roll their dither on the params
            base_key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(0), step), 2
            )
        for i, name in enumerate(self.names):
            chunk = self.bucket_chunk(shard, i)
            if chunk.size == 0:
                for leaf_id, leaf in self._unpack_bucket(
                    i, jnp.zeros((0,), jnp.float32)
                ):
                    out[leaf_id] = leaf
                continue
            with _phase("param_gather"):
                if quantize:
                    from apex_tpu.ops.quantization import (
                        quantized_all_gather,
                    )

                    res = (residuals[name]["ag"] if use_ef else None)
                    key = (jax.random.fold_in(base_key, i)
                           if base_key is not None else None)
                    full, new_ag = quantized_all_gather(
                        chunk, shard_axis, cfg, residual=res,
                        step=step, key=key,
                    )
                else:
                    from apex_tpu.transformer.tensor_parallel.mappings \
                        import all_gather_invariant

                    # cast BEFORE the gather: elementwise, so the
                    # result is bit-identical to gathering fp32 and
                    # casting after — at half the wire bytes for bf16
                    full = all_gather_invariant(
                        chunk.astype(self.plan.buckets[i].dtype),
                        shard_axis, axis=0, tiled=True,
                    )
                    new_ag = None
            if new_ag is not None and new_residuals is not None:
                new_residuals[name]["ag"] = new_ag
            for leaf_id, leaf in self._unpack_bucket(i, full):
                out[leaf_id] = leaf
        params = jax.tree_util.tree_unflatten(self.treedef, out)
        return params, (new_residuals if residuals is not None else None)

    def reduce_scatter_grads(
        self,
        grads: Any,
        axis_name: Any,
        compression: Any = None,
        residuals: Optional[dict] = None,
        step=None,
    ) -> Tuple[jnp.ndarray, Optional[dict]]:
        """Per-bucket RS(ici) → AR(dcn) of the gradients, straight into
        the shard layout: returns the raw SUM over the data axes of
        this rank's ``(shard_size,)`` gradient chunk (callers divide by
        the world for the mean — the ZeRO step's convention) plus the
        updated grad-leg residuals.  With ``compression`` the dcn leg
        runs int8 (and, under ``ici_legs``, the RS leg too); with a
        flat ``axis_name`` the reduce is one ``psum_scatter`` per
        bucket and compression must be None."""
        from apex_tpu.ops.quantization import as_compression_config

        cfg = as_compression_config(compression)
        dcn_axis, shard_axis = _split_axes(axis_name)
        if cfg is not None and dcn_axis is None:
            raise ValueError(
                "compression quantizes the DCN leg of the hierarchical "
                "reduce: pass axis_name=(dcn_axis, ici_axis)"
            )
        use_ef = (cfg is not None and cfg.error_feedback
                  and residuals is not None)
        leaves = self.treedef.flatten_up_to(grads)
        bufs = self.plan.pack(leaves)
        base_keys = [None, None]
        if (cfg is not None and cfg.rounding == "stochastic"
                and step is not None):
            base = jax.random.fold_in(jax.random.PRNGKey(0), step)
            base_keys = [jax.random.fold_in(base, 0),   # dcn leg
                         jax.random.fold_in(base, 1)]   # grad RS leg
        new_residuals: Optional[dict] = (
            {k: dict(v) for k, v in residuals.items()}
            if residuals is not None else None
        )
        from apex_tpu.telemetry.spans import phase as _phase

        parts = []
        for i, name in enumerate(self.names):
            buf = bufs[i].astype(jnp.float32)
            if buf.size == 0:
                parts.append(jnp.zeros((0,), jnp.float32))
                continue
            padded = self.padded[i]
            if padded != buf.size:
                buf = jnp.concatenate(
                    [buf, jnp.zeros((padded - buf.size,), jnp.float32)]
                )
            with _phase("grad_sync"):
                if cfg is not None and cfg.ici_legs:
                    from apex_tpu.ops.quantization import (
                        quantized_reduce_scatter,
                    )

                    res = (residuals[name]["ici_push"] if use_ef
                           else None)
                    key = (jax.random.fold_in(base_keys[1], i)
                           if base_keys[1] is not None else None)
                    chunk, new_rs = quantized_reduce_scatter(
                        buf, shard_axis, cfg, residual=res,
                        step=step, key=key,
                    )
                    if new_rs is not None and new_residuals is not None:
                        new_residuals[name]["ici_push"] = new_rs
                else:
                    chunk = jax.lax.psum_scatter(
                        buf, shard_axis, tiled=True
                    )
                if dcn_axis is not None:
                    if cfg is not None:
                        from apex_tpu.ops.quantization import (
                            quantized_psum,
                        )

                        res = None
                        if use_ef:
                            res = {"push": residuals[name]["push"],
                                   "pull": residuals[name]["pull"]}
                        key = (jax.random.fold_in(base_keys[0], i)
                               if base_keys[0] is not None else None)
                        chunk, new_dcn = quantized_psum(
                            chunk, dcn_axis, cfg, residual=res,
                            step=step, key=key,
                        )
                        if (new_dcn is not None
                                and new_residuals is not None):
                            new_residuals[name]["push"] = \
                                new_dcn["push"]
                            new_residuals[name]["pull"] = \
                                new_dcn["pull"]
                    else:
                        chunk = jax.lax.psum(chunk, dcn_axis)
            parts.append(chunk)
        shard = (jnp.concatenate(parts) if parts
                 else jnp.zeros((0,), jnp.float32))
        return shard, (new_residuals if residuals is not None else None)

    # ------------------------------------------------------- telemetry
    def _emit_gather_events(self, axis_name, cfg) -> None:
        """One ``param_gather`` event per bucket at trace time — static
        host ints only, free with no sink registered (the comm_bucket
        convention from PR 4/6); wire bytes are ring-model ESTIMATES of
        the AG leg, int8 payload + fp32 scale sidecar when compressed,
        model-dtype payload otherwise."""
        if not _events.have_sinks():
            return
        from apex_tpu.telemetry.events import ring_wire_bytes

        _, shard_axis = _split_axes(axis_name)
        ici = _axis_size(shard_axis)
        quantize = cfg is not None and cfg.ici_legs
        for i, (name, b) in enumerate(
            zip(self.names, self.plan.buckets)
        ):
            padded, chunk = self.padded[i], self.chunk_sizes[i]
            itemsize = int(np.dtype(b.dtype).itemsize)
            if quantize:
                nb = max(-(-chunk // cfg.block_size), 1)
                result_bytes = ici * (chunk + nb * 4)
            else:
                result_bytes = padded * itemsize
            _events.emit(
                "param_gather",
                where="zero3",
                bucket=name,
                elements=int(b.size),
                dtype=str(np.dtype(b.dtype).name),
                bytes=int(b.size) * itemsize,
                ici_size=int(ici),
                compressed=bool(quantize),
                ag_ici_wire_bytes=round(ring_wire_bytes(
                    "all-gather", ici, result_bytes,
                    result_bytes=result_bytes,
                )),
            )

    # ------------------------------------------------------- residuals
    def residual_sizes(self, dcn: int, ici: int, cfg) -> dict:
        """Per-bucket error-feedback buffer lengths for this layout
        under ``cfg`` (the ONE sizing, from
        :func:`~apex_tpu.ops.quantization.zero3_residual_sizes`)."""
        from apex_tpu.ops.quantization import zero3_residual_sizes

        return {
            name: zero3_residual_sizes(
                b.size, dcn, ici, cfg.block_size, cfg.ici_legs
            )
            for name, b in zip(self.names, self.plan.buckets)
        }


def zero3_comm_state(layout: Zero3Layout, axis_name, compression,
                     mesh=None) -> dict:
    """Zero per-bucket error-feedback residuals for a ZeRO-3 layout:
    grad legs (``push``/``pull`` for the dcn all-reduce, ``ici_push``
    for the int8 RS) plus the ``ag`` param-gather residual under
    ``ici_legs``.  Host-side with ``mesh`` (global buffers, one slice
    per (dcn, ici) position — ``ag`` rides ici only, it is invariant
    over dcn like the shard it compensates); per-device inside
    ``shard_map`` without."""
    from apex_tpu.ops.quantization import as_compression_config

    cfg = as_compression_config(compression)
    if cfg is None:
        raise ValueError("zero3_comm_state needs a compression config")
    dcn_axis, ici_axis = _split_axes(axis_name)
    if dcn_axis is None:
        raise ValueError(
            "compressed ZeRO-3 comm state needs the hierarchical "
            "(dcn, ici) axis pair"
        )
    if mesh is not None:
        dcn, ici = mesh.shape[dcn_axis], mesh.shape[ici_axis]
    else:
        dcn, ici = _axis_size(dcn_axis), _axis_size(ici_axis)
    sizes = layout.residual_sizes(dcn, ici, cfg)
    residuals = {}
    for name, per in sizes.items():
        residuals[name] = {}
        for k, n in per.items():
            reps = 1
            if mesh is not None:
                # ag is replicated across dcn (it compensates the
                # dcn-invariant shard); everything else varies over
                # both data axes
                reps = ici if k == "ag" else dcn * ici
            residuals[name][k] = jnp.zeros((reps * n,), jnp.float32)
    return residuals


def zero3_comm_specs(layout: Zero3Layout, axis_name, compression,
                     model_axes: Sequence[str] = ()) -> dict:
    """shard_map / device_put specs for :func:`zero3_comm_state`."""
    from apex_tpu.ops.quantization import as_compression_config

    from jax.sharding import PartitionSpec as P

    cfg = as_compression_config(compression)
    dcn_axis, ici_axis = _split_axes(axis_name)
    sizes = layout.residual_sizes(2, 2, cfg)  # key sets only
    out = {}
    for name, per in sizes.items():
        out[name] = {
            k: (P((*model_axes, ici_axis)) if k == "ag"
                else P((*model_axes, dcn_axis, ici_axis)))
            for k in per
        }
    return out

"""SyncBatchNorm — cross-replica batch normalization.

The reference needs 1.7k lines of Welford CUDA kernels plus an
all_gather/merge dance (reference: csrc/welford.cu,
apex/parallel/optimized_sync_batchnorm_kernel.py:1-119).  On TPU the
whole thing is a single fused ``psum`` of the sufficient statistics
(count, Σx, Σx²) over the 'dp' mesh axis — numerically equivalent to
parallel Welford merging, and it supports different per-replica batch
sizes the same way (counts are summed, not assumed equal).

Matches reference semantics:
- biased variance for normalization, unbiased for running stats
  (reference: apex/parallel/sync_batchnorm.py:105-117),
- eval mode uses running stats (falls back to plain batch_norm,
  reference: optimized_sync_batchnorm.py:9-85),
- optional fused ReLU epilogue (``fuse_relu``),
- channels-last is the native layout here (feature axis defaults to -1).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from apex_tpu._compat import axis_size as _axis_size

__all__ = ["sync_batch_norm", "SyncBatchNorm"]


def sync_batch_norm(
    x: jnp.ndarray,
    weight: Optional[jnp.ndarray],
    bias: Optional[jnp.ndarray],
    running_mean: Optional[jnp.ndarray],
    running_var: Optional[jnp.ndarray],
    training: bool = True,
    momentum: float = 0.1,
    eps: float = 1e-5,
    axis_name: Optional[str] = None,
    process_group_size: int = 0,
    fuse_relu: bool = False,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """Functional SyncBN over the trailing feature axis.

    Returns ``(out, new_running_mean, new_running_var)``.  When
    ``axis_name`` is given and we're inside an SPMD context, statistics
    are reduced across that mesh axis.  ``process_group_size`` reproduces
    ``create_syncbn_process_group`` (reference:
    apex/parallel/__init__.py:35-95): stats are reduced within groups of
    that size instead of the whole axis (0 = whole axis).
    """
    feat = x.shape[-1]
    reduce_axes = tuple(range(x.ndim - 1))

    if not training:
        mean, var = running_mean, running_var
        xf = x.astype(jnp.float32)
        inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
        out = (xf - mean.astype(jnp.float32)) * inv
        if weight is not None:
            out = out * weight.astype(jnp.float32)
        if bias is not None:
            out = out + bias.astype(jnp.float32)
        out = out.astype(x.dtype)
        if fuse_relu:
            out = jax.nn.relu(out)
        return out, running_mean, running_var

    xf = x.astype(jnp.float32)
    local_count = jnp.float32(xf.size // feat)
    local_sum = jnp.sum(xf, axis=reduce_axes)
    local_sumsq = jnp.sum(jnp.square(xf), axis=reduce_axes)

    if axis_name is not None:
        if process_group_size and process_group_size > 0:
            # group-limited reduction: psum over contiguous index groups
            idx = jax.lax.axis_index(axis_name)
            group = idx // process_group_size
            stacked_c = jax.lax.all_gather(local_count, axis_name)
            stacked_s = jax.lax.all_gather(local_sum, axis_name)
            stacked_q = jax.lax.all_gather(local_sumsq, axis_name)
            world = _axis_size(axis_name)
            members = (jnp.arange(world) // process_group_size) == group
            count = jnp.sum(jnp.where(members, stacked_c, 0.0))
            total_sum = jnp.sum(
                jnp.where(members[:, None], stacked_s, 0.0), axis=0
            )
            total_sumsq = jnp.sum(
                jnp.where(members[:, None], stacked_q, 0.0), axis=0
            )
        else:
            count = jax.lax.psum(local_count, axis_name)
            total_sum = jax.lax.psum(local_sum, axis_name)
            total_sumsq = jax.lax.psum(local_sumsq, axis_name)
    else:
        count, total_sum, total_sumsq = local_count, local_sum, local_sumsq

    mean = total_sum / count
    var = total_sumsq / count - jnp.square(mean)  # biased, for normalization

    inv = jax.lax.rsqrt(var + eps)
    out = (xf - mean) * inv
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    out = out.astype(x.dtype)
    if fuse_relu:
        out = jax.nn.relu(out)

    new_rm, new_rv = running_mean, running_var
    if running_mean is not None:
        unbiased = var * (count / jnp.maximum(count - 1.0, 1.0))
        new_rm = (1 - momentum) * running_mean + momentum * mean
        new_rv = (1 - momentum) * running_var + momentum * unbiased
    return out, new_rm, new_rv


class SyncBatchNorm(nn.Module):
    """flax module form (reference: apex/parallel/optimized_sync_batchnorm.py).

    Running stats live in the 'batch_stats' collection; pass
    ``use_running_average=True`` (or ``deterministic``) for eval.
    """

    # None → inferred from the input's trailing (channel) dim at call
    num_features: Optional[int] = None
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = None
    process_group_size: int = 0
    fuse_relu: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        num_features = (
            self.num_features if self.num_features is not None
            else x.shape[-1]
        )
        weight = bias = None
        if self.affine:
            weight = self.param(
                "weight", nn.initializers.ones, (num_features,),
                self.param_dtype,
            )
            bias = self.param(
                "bias", nn.initializers.zeros, (num_features,),
                self.param_dtype,
            )
        ra_mean = self.variable(
            "batch_stats", "running_mean",
            lambda: jnp.zeros((num_features,), jnp.float32),
        )
        ra_var = self.variable(
            "batch_stats", "running_var",
            lambda: jnp.ones((num_features,), jnp.float32),
        )
        training = not use_running_average
        out, new_rm, new_rv = sync_batch_norm(
            x,
            weight,
            bias,
            ra_mean.value if self.track_running_stats else None,
            ra_var.value if self.track_running_stats else None,
            training=training,
            momentum=self.momentum,
            eps=self.eps,
            axis_name=self.axis_name,
            process_group_size=self.process_group_size,
            fuse_relu=self.fuse_relu,
        )
        if training and self.track_running_stats and not self.is_initializing():
            ra_mean.value = new_rm
            ra_var.value = new_rv
        return out

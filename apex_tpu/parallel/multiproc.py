"""Multi-process launcher for multi-host SPMD runs.

Capability match of ``python -m apex.parallel.multiproc``
(reference: apex/parallel/multiproc.py:1-35 — the pre-torchrun
one-process-per-GPU local launcher).  On TPU, multi-host JAX uses one
process per host with ``jax.distributed.initialize``; this launcher
spawns N local processes wired together through a local coordinator so
the multi-host code path (process_index/process_count, cross-host
collectives over DCN) can be exercised on a single machine::

    python -m apex_tpu.parallel.multiproc --nprocs 2 train.py --args...

Each child gets APEX_TPU_PROCESS_ID / APEX_TPU_NUM_PROCESSES /
APEX_TPU_COORDINATOR env vars; call :func:`initialize_distributed` at
the top of the script to join the cluster (the analog of the
reference's ``initialize_distributed`` env-var recipe,
apex/transformer/testing/commons.py:81-113).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

__all__ = ["initialize_distributed", "main"]


def initialize_distributed() -> None:
    """Join the process group described by the launcher's env vars (or
    no-op when running single-process)."""
    nproc = int(os.environ.get("APEX_TPU_NUM_PROCESSES", "1"))
    if nproc <= 1:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ["APEX_TPU_COORDINATOR"],
        num_processes=nproc,
        process_id=int(os.environ["APEX_TPU_PROCESS_ID"]),
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="spawn N local processes for multi-host-style SPMD"
    )
    ap.add_argument("--nprocs", type=int, default=2)
    ap.add_argument("--port", type=int, default=12355)
    ap.add_argument("script", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.script:
        ap.error("no script given")

    procs = []
    for rank in range(args.nprocs):
        env = dict(os.environ)
        env["APEX_TPU_PROCESS_ID"] = str(rank)
        env["APEX_TPU_NUM_PROCESSES"] = str(args.nprocs)
        env["APEX_TPU_COORDINATOR"] = f"127.0.0.1:{args.port}"
        procs.append(
            subprocess.Popen([sys.executable] + args.script, env=env)
        )
    rc = 0
    for p in procs:
        rc = rc or p.wait()
    return rc


if __name__ == "__main__":
    raise SystemExit(main())

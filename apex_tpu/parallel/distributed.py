"""Data-parallel gradient synchronization.

The reference DDP (reference: apex/parallel/distributed.py:129-640) does
four jobs: broadcast params at init, discover grad buckets in backward
order, allreduce buckets on side streams overlapped with backward, and
optionally keep flat allreduce buffers for amp.  Under SPMD every one of
those collapses:

- param broadcast   → params are replicated by sharding (``NamedSharding``
  with no 'dp' axis in the spec);
- bucketing/streams → one ``psum`` of the whole grad pytree; XLA chunks
  and overlaps it with the backward automatically;
- flat buffers      → jit's problem, not ours.

What survives as *semantics* are the knobs, reproduced here exactly:
``gradient_average`` (divide by world size), ``gradient_predivide_factor``
(divide by f before the reduce and by world/f after,
reference: distributed.py:463-476), and ``allreduce_always_fp32``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "data_parallel_mesh",
    "hierarchical_data_parallel_mesh",
    "all_reduce_gradients",
    "DistributedDataParallel",
    "Reducer",
]


def _axis_size(axis_name):
    """Version-portable ``jax.lax.axis_size`` (absent in jax 0.4.x,
    where the axis extent comes from the bound mesh context)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def data_parallel_mesh(
    devices: Optional[Sequence] = None, axis_name: str = "dp"
) -> Mesh:
    """A 1-D mesh over all (or the given) devices — the analog of the
    default NCCL world process group."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (axis_name,))


def hierarchical_data_parallel_mesh(
    ici_size: int,
    devices: Optional[Sequence] = None,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
) -> Mesh:
    """A 2-D ("dcn", "ici") data-parallel mesh: ``ici_size`` devices per
    fast-interconnect group, the rest across the slow axis — the TPU
    analog of the reference's ``dwu_group_size`` intra/inter-group split
    (reference: apex/contrib/optimizers/distributed_fused_adam.py:115-116).
    Devices within a physical pod slice should be contiguous so the ici
    axis rides ICI links."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % ici_size:
        raise ValueError(
            f"device count ({len(devices)}) not divisible by ici group "
            f"size ({ici_size})"
        )
    grid = np.asarray(devices).reshape(-1, ici_size)
    return Mesh(grid, (dcn_axis, ici_axis))


def _hierarchical_psum(g: jnp.ndarray, dcn_axis: str, ici_axis: str):
    """All-reduce over both data axes as RS(ici) → AR(dcn) → AG(ici):
    mathematically ``psum`` over (dcn, ici), but each DCN message is only
    1/ici of the tensor (the reference's 2-level reduce,
    distributed_fused_adam.py:106-160)."""
    n = g.size
    ici = _axis_size(ici_axis)
    flat = g.reshape(-1)
    pad = (-n) % ici
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunk = jax.lax.psum_scatter(flat, ici_axis, tiled=True)
    chunk = jax.lax.psum(chunk, dcn_axis)
    out = jax.lax.all_gather(chunk, ici_axis, axis=0, tiled=True)
    if pad:
        out = out[:n]
    return out.reshape(g.shape)


def all_reduce_gradients(
    grads: Any,
    axis_name: Any = "dp",
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    allreduce_always_fp32: bool = False,
) -> Any:
    """psum the grad pytree over ``axis_name`` (call inside shard_map/pmap).

    ``axis_name`` may also be a nested ``(dcn_axis, ici_axis)`` pair: the
    all-reduce is then decomposed into reduce-scatter within ici,
    all-reduce across dcn and all-gather within ici, so only 1/ici of the
    gradient bytes cross the slow interconnect (the reference's 2-level
    hierarchy, apex/contrib/optimizers/distributed_fused_adam.py:106-160).

    Matches the reference's scaling semantics
    (reference: apex/parallel/distributed.py:463-476): grads are divided
    by ``predivide_factor`` before the reduction and by
    ``world_size / predivide_factor`` after, which in exact arithmetic is
    a mean over the axis but controls intermediate magnitude in fp16.
    """
    hierarchical = isinstance(axis_name, (tuple, list))
    if hierarchical:
        dcn_axis, ici_axis = axis_name
        world = _axis_size(dcn_axis) * _axis_size(ici_axis)
    else:
        world = _axis_size(axis_name)

    def sync(g):
        orig_dtype = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        if hierarchical:
            g = _hierarchical_psum(g, dcn_axis, ici_axis)
        else:
            g = jax.lax.psum(g, axis_name)
        if gradient_average:
            post = world / gradient_predivide_factor
            if post != 1.0:
                g = g / post
        elif gradient_predivide_factor != 1.0:
            g = g * gradient_predivide_factor
        return g.astype(orig_dtype)

    return jax.tree.map(sync, grads)


class DistributedDataParallel:
    """Configuration object for DP gradient sync.

    Use either as a callable on a grad pytree inside an SPMD context::

        ddp = DistributedDataParallel(axis_name="dp")
        grads = ddp(grads)          # inside shard_map

    or let it build the whole sharded value-and-grad for you::

        grad_fn = ddp.value_and_grad(loss_fn, mesh)
        (loss, grads) = grad_fn(params, batch)   # batch sharded over dp

    The constructor knobs mirror the reference's
    (reference: apex/parallel/distributed.py:139-206); the
    stream/bucket/message-size knobs have no TPU meaning and are
    accepted-and-ignored for source compatibility.
    """

    def __init__(
        self,
        axis_name: str = "dp",
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        allreduce_always_fp32: bool = False,
        # accepted for source compat; meaningless under XLA:
        message_size: int = 10000000,
        delay_allreduce: bool = False,
        num_allreduce_streams: int = 1,
        retain_allreduce_buffers: bool = False,
    ):
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32

    def __call__(self, grads: Any) -> Any:
        return all_reduce_gradients(
            grads,
            axis_name=self.axis_name,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            allreduce_always_fp32=self.allreduce_always_fp32,
        )

    def value_and_grad(
        self,
        loss_fn: Callable,
        mesh: Mesh,
        has_aux: bool = False,
    ) -> Callable:
        """Build ``(params, batch) -> (loss, grads)`` with params replicated,
        batch sharded over ``axis_name``, and grads synced."""
        from jax.sharding import PartitionSpec as P

        shard_map = jax.shard_map

        axis = self.axis_name

        def local_step(params, batch):
            out, grads = jax.value_and_grad(loss_fn, has_aux=has_aux)(
                params, batch
            )
            grads = self(grads)
            if has_aux:
                loss, aux = out
                return jax.lax.pmean(loss, axis), aux, grads
            return jax.lax.pmean(out, axis), grads

        batch_spec = P(axis)
        rep = P()
        out_specs = (rep, rep, rep) if has_aux else (rep, rep)
        return jax.jit(
            shard_map(
                local_step,
                mesh=mesh,
                in_specs=(rep, batch_spec),
                out_specs=out_specs,
                check_vma=False,
            )
        )


class Reducer:
    """Deferred, user-triggered gradient reduction — the functional
    analog of the reference's manual-control DDP alternative
    (reference: apex/parallel/distributed.py:89-126, whose point is
    that unlike DDP nothing syncs during backward; the user calls
    ``reduce()`` when ready, e.g. every K accumulation steps).

    Usage inside a shard_map'd step::

        red = Reducer(axis_name="dp")             # static config
        acc = red.init(params)                    # zeros pytree
        w_local = jax.lax.pcast(params, "dp", to="varying")  # see below
        for k in ...:                             # K times, NO collective
            acc = red.accumulate(acc, jax.grad(local_loss)(w_local, mb[k]))
        mean_grads, acc = red.reduce(acc)         # ONE psum-mean + reset

    The varying-cast is load-bearing: under shard_map, differentiating a
    device-LOCAL (varying) loss with respect to REPLICATED params makes
    JAX insert the reduction itself (the transpose of the replicated→
    varying broadcast is a psum), so "the local gradient before
    reduction" would not exist to defer.  Marking the params varying
    first keeps the per-device gradients local until ``reduce`` — which
    is the entire point of the reference's Reducer (delaying the
    allreduce across accumulation steps).

    Scaling semantics — a DELIBERATE DEVIATION from the reference: the
    reference's Reducer averages only over the world size
    (apex/parallel/distributed.py), returning the SUM over the K
    locally accumulated microbatches.  Here ``gradient_average=True``
    (default) also divides by K, yielding the mean gradient over
    (axis world x K local steps) — so the effective learning rate does
    not silently scale with the accumulation count.  Pass
    ``average_over_microbatches=False`` to reproduce the reference
    scaling exactly (mean over world, sum over K — what you want when
    porting a reference training recipe whose lr schedule was tuned
    against that convention); with ``gradient_average=False`` both
    flags yield the raw sum over both.  ``allreduce_always_fp32`` is
    accepted for signature parity but meaningless here — the
    accumulator is ALWAYS fp32 (see :meth:`init`), so the reduction
    already runs in fp32 regardless.
    """

    def __init__(
        self,
        axis_name: Any = "dp",
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        allreduce_always_fp32: bool = False,
        average_over_microbatches: bool = True,
    ):
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.average_over_microbatches = average_over_microbatches

    def init(self, params: Any) -> dict:
        """Zero accumulator state (fp32 buffers — accumulation across
        microbatches in bf16 loses low-order contributions)."""
        return {
            "sum": jax.tree.map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
            ),
            "count": jnp.zeros((), jnp.int32),
        }

    def accumulate(self, state: dict, grads: Any) -> dict:
        """Add one microbatch's grads locally — no collective runs."""
        return {
            "sum": jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), state["sum"], grads
            ),
            "count": state["count"] + 1,
        }

    def reduce(self, state: dict) -> tuple:
        """One collective over everything accumulated; returns
        ``(grads, fresh_state)`` — the mean over (world x count) when
        ``gradient_average`` (over world only when
        ``average_over_microbatches=False``, the reference scaling),
        the raw sum otherwise."""
        if self.gradient_average and self.average_over_microbatches:
            n = jnp.maximum(state["count"], 1).astype(jnp.float32)
            grads = jax.tree.map(lambda a: a / n, state["sum"])
        else:
            grads = state["sum"]
        grads = all_reduce_gradients(
            grads,
            axis_name=self.axis_name,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            allreduce_always_fp32=self.allreduce_always_fp32,
        )
        fresh = {
            "sum": jax.tree.map(jnp.zeros_like, state["sum"]),
            "count": jnp.zeros((), jnp.int32),
        }
        return grads, fresh

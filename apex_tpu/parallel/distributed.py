"""Data-parallel gradient synchronization.

The reference DDP (reference: apex/parallel/distributed.py:129-640) does
four jobs: broadcast params at init, discover grad buckets in backward
order, allreduce buckets on side streams overlapped with backward, and
optionally keep flat allreduce buffers for amp.  Under SPMD:

- param broadcast   → params are replicated by sharding (``NamedSharding``
  with no 'dp' axis in the spec);
- flat buffers      → jit's problem, not ours;
- bucketing/streams → NOT automatic.  A single ``psum`` of the whole
  grad pytree issued AFTER the accumulation loop (the deferred
  ``Reducer`` pattern below) leaves XLA's latency-hiding scheduler no
  independent compute to hide the collective behind — the whole
  reduce latency is exposed.  The overlap the reference hand-built
  with side streams is restored by :mod:`apex_tpu.parallel.overlap`:
  ``overlap_grad_sync=True`` assembles size-targeted buckets in
  reverse-layer (backward-ready) order and, in the pipelined
  accumulate-and-reduce loop, issues microbatch *i*'s bucket reduces
  while microbatch *i+1*'s fwd/bwd computes, so the scheduler can emit
  async ``all-reduce-start``/``-done`` pairs with real compute between
  them.  ``bucket_bytes`` is the TPU analog of the reference's
  ``message_size``/``allreduce_communicators`` knobs; the trade
  (per-microbatch reduces cost K× the bytes of one deferred reduce,
  in exchange for hiding the latency) is documented in
  docs/distributed.md.  ``overlap_grad_sync=False`` (default) is the
  unchanged deferred path, and single-shot bucketed reduces at
  ``compression=None`` are bit-identical to the unbucketed ones
  (collectives are elementwise — packing changes no per-element
  summation order).

What survives as *semantics* are the knobs, reproduced here exactly:
``gradient_average`` (divide by world size), ``gradient_predivide_factor``
(divide by f before the reduce and by world/f after,
reference: distributed.py:463-476), and ``allreduce_always_fp32``.

Compressed collectives: with a hierarchical ``(dcn_axis, ici_axis)``
axis pair, ``compression="int8"`` block-quantizes the DCN leg of
the reduce (:mod:`apex_tpu.ops.quantization`): the ici-reduced chunk is
quantized once, exchanged over dcn as int8 values + per-block fp32
scales, dequantized once — by default the ICI reduce-scatter/
all-gather legs and the returned gradient dtype are untouched, and
``compression=None`` is bit-identical to the uncompressed path.
``CompressionConfig(ici_legs=True)`` additionally runs BOTH ICI legs
int8 (EQuARX's ICI half — ~4x fewer bytes on the fast links too,
chunk boundaries preserved so nothing else moves).  Error feedback
(on by default) carries the per-device quantization residual as
explicit state: build it with :func:`init_comm_state` (it sizes the
extra ``ici_push``/``ici_pull`` buffers from the config), thread it
through ``all_reduce_gradients(..., comm_state=...)`` (or the
``DistributedDataParallel``/``Reducer`` equivalents), and checkpoint it
with the rest of the training state.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.telemetry import events as _events

__all__ = [
    "data_parallel_mesh",
    "hierarchical_data_parallel_mesh",
    "all_reduce_gradients",
    "init_comm_state",
    "comm_state_specs",
    "DistributedDataParallel",
    "Reducer",
]


def _axis_size(axis_name):
    """Version-portable ``jax.lax.axis_size`` (absent in jax 0.4.x,
    where the axis extent comes from the bound mesh context)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def data_parallel_mesh(
    devices: Optional[Sequence] = None, axis_name: str = "dp"
) -> Mesh:
    """A 1-D mesh over all (or the given) devices — the analog of the
    default NCCL world process group."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (axis_name,))


def hierarchical_data_parallel_mesh(
    ici_size: int,
    devices: Optional[Sequence] = None,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
) -> Mesh:
    """A 2-D ("dcn", "ici") data-parallel mesh: ``ici_size`` devices per
    fast-interconnect group, the rest across the slow axis — the TPU
    analog of the reference's ``dwu_group_size`` intra/inter-group split
    (reference: apex/contrib/optimizers/distributed_fused_adam.py:115-116).
    Devices within a physical pod slice should be contiguous so the ici
    axis rides ICI links."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % ici_size:
        raise ValueError(
            f"device count ({len(devices)}) not divisible by ici group "
            f"size ({ici_size})"
        )
    grid = np.asarray(devices).reshape(-1, ici_size)
    return Mesh(grid, (dcn_axis, ici_axis))


def _hierarchical_psum(g: jnp.ndarray, dcn_axis: str, ici_axis: str,
                       compression=None, residual=None, step=None,
                       key=None):
    """All-reduce over both data axes as RS(ici) → AR(dcn) → AG(ici):
    mathematically ``psum`` over (dcn, ici), but each DCN message is only
    1/ici of the tensor (the reference's 2-level reduce,
    distributed_fused_adam.py:106-160).

    With ``compression`` given, the AR(dcn) middle leg runs as an int8
    block-quantized all-reduce (:func:`apex_tpu.ops.quantization.
    quantized_psum`) — by default the ICI legs and the output dtype are
    untouched, and ``compression=None`` takes the exact uncompressed
    path.  With ``compression.ici_legs`` the RS/AG legs ALSO go int8
    (EQuARX's ICI half): :func:`~apex_tpu.ops.quantization.
    quantized_reduce_scatter` replaces the full-width ``psum_scatter``
    (chunk boundaries preserved, so the dcn leg and its residual sizes
    are unchanged) and :func:`~apex_tpu.ops.quantization.
    quantized_all_gather` replaces the gather, each with its own
    error-feedback buffer (``ici_push``/``ici_pull`` in the residual
    dict).  Returns ``(out, new_residual)``; ``new_residual`` is None
    unless an error-feedback ``residual`` dict was passed."""
    from apex_tpu.transformer.tensor_parallel.mappings import (
        all_gather_invariant,
    )

    n = g.size
    ici = _axis_size(ici_axis)
    flat = g.reshape(-1)
    pad = (-n) % ici
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    ici_legs = compression is not None and compression.ici_legs
    if ici_legs and residual is not None and "ici_push" not in residual:
        raise ValueError(
            "compression.ici_legs=True but the comm state has no "
            "ici_push/ici_pull residuals: rebuild it with "
            "init_comm_state(..., compression=<the ici_legs config>)"
        )
    if not ici_legs and residual is not None and "ici_push" in residual:
        # the opposite mismatch would silently DROP the ici residuals
        # from the returned state (an opaque out_specs/pytree error at
        # best) — refuse with the same rebuild message
        raise ValueError(
            "the comm state carries ici_push/ici_pull residuals but "
            "compression.ici_legs is False: rebuild it with "
            "init_comm_state(..., compression=<this config>) or turn "
            "ici_legs back on"
        )
    # one base dither key per (leaf, step), decorrelated per leg —
    # sharing the caller's key across the three quantization sites
    # would re-roll the same noise on different data
    leg_key = lambda i: None
    if compression is not None and compression.rounding == "stochastic":
        base = key
        if base is None and step is not None:
            import jax as _jax

            base = _jax.random.fold_in(_jax.random.PRNGKey(0), step)
        if base is not None:
            import jax as _jax

            leg_key = lambda i: _jax.random.fold_in(base, i)
    new_residual = None
    new_ici_push = new_ici_pull = None
    if ici_legs:
        from apex_tpu.ops.quantization import quantized_reduce_scatter

        chunk, new_ici_push = quantized_reduce_scatter(
            flat.astype(jnp.float32), ici_axis, compression,
            residual=None if residual is None else residual["ici_push"],
            step=step, key=leg_key(1),
        )
    else:
        chunk = jax.lax.psum_scatter(flat, ici_axis, tiled=True)
    if compression is None:
        chunk = jax.lax.psum(chunk, dcn_axis)
    else:
        from apex_tpu.ops.quantization import quantized_psum

        dcn_residual = None
        if residual is not None:
            dcn_residual = {"push": residual["push"],
                            "pull": residual["pull"]}
        chunk, new_dcn = quantized_psum(
            chunk, dcn_axis, compression, residual=dcn_residual,
            step=step, key=leg_key(0) if ici_legs else key,
        )
        if residual is not None:
            new_residual = dict(new_dcn)
    if ici_legs:
        from apex_tpu.ops.quantization import quantized_all_gather

        out, new_ici_pull = quantized_all_gather(
            chunk.astype(jnp.float32), ici_axis, compression,
            residual=None if residual is None else residual["ici_pull"],
            step=step, key=leg_key(2),
        )
        out = out.astype(flat.dtype)
    else:
        # invariant-typed gather: every ici rank receives the identical
        # dcn-reduced chunk, so the result is replicated over both data
        # axes and downstream P() out_specs typecheck (same HLO either
        # way)
        out = all_gather_invariant(chunk, ici_axis, axis=0, tiled=True)
    if new_residual is not None and new_ici_push is not None:
        new_residual["ici_push"] = new_ici_push
        new_residual["ici_pull"] = new_ici_pull
    if pad:
        out = out[:n]
    return out.reshape(g.shape), new_residual


def all_reduce_gradients(
    grads: Any,
    axis_name: Any = "dp",
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    allreduce_always_fp32: bool = False,
    compression: Any = None,
    comm_state: Optional[dict] = None,
    overlap_grad_sync: bool = False,
    bucket_bytes: Optional[int] = None,
) -> Any:
    """psum the grad pytree over ``axis_name`` (call inside shard_map/pmap).

    ``axis_name`` may also be a nested ``(dcn_axis, ici_axis)`` pair: the
    all-reduce is then decomposed into reduce-scatter within ici,
    all-reduce across dcn and all-gather within ici, so only 1/ici of the
    gradient bytes cross the slow interconnect (the reference's 2-level
    hierarchy, apex/contrib/optimizers/distributed_fused_adam.py:106-160).

    ``compression`` (None | "int8" |
    :class:`~apex_tpu.ops.quantization.CompressionConfig`) additionally
    quantizes the DCN leg of the hierarchical pair to int8 + per-block
    fp32 scales; it requires a hierarchical ``axis_name``, leaves the
    ICI legs and gradient dtypes untouched, and ``None`` is
    bit-identical to the uncompressed reduce.  With error feedback (the
    config default) pass ``comm_state`` (from :func:`init_comm_state`);
    the call then returns ``(grads, new_comm_state)`` instead of just
    ``grads`` — thread the new state into the next step and checkpoint
    it with the training state.

    ``overlap_grad_sync=True`` reduces size-targeted BUCKETS of leaves
    (reverse-layer order, ``bucket_bytes`` per bucket — see
    :mod:`apex_tpu.parallel.overlap`) instead of one collective per
    leaf, giving the scheduler separately-overlappable collectives; at
    ``compression=None`` the result is bit-identical to the unbucketed
    reduce.  With compression the ``comm_state`` must then be BUCKETED
    too: build it with ``init_comm_state(..., bucket_bytes=...)`` using
    the same bucket size and leaf dtypes.

    Matches the reference's scaling semantics
    (reference: apex/parallel/distributed.py:463-476): grads are divided
    by ``predivide_factor`` before the reduction and by
    ``world_size / predivide_factor`` after, which in exact arithmetic is
    a mean over the axis but controls intermediate magnitude in fp16.
    """
    from apex_tpu.ops.quantization import as_compression_config

    cfg = as_compression_config(compression)
    hierarchical = isinstance(axis_name, (tuple, list))
    if cfg is not None and not hierarchical:
        raise ValueError(
            "compression quantizes the DCN leg of a hierarchical "
            "reduce: pass axis_name=(dcn_axis, ici_axis)"
        )
    if cfg is not None and comm_state is None and (
        cfg.error_feedback or cfg.rounding == "stochastic"
    ):
        raise ValueError(
            "this compression config needs explicit comm state (error "
            "feedback carries residuals; stochastic rounding derives "
            "its per-step key from the state's counter): build it with "
            "init_comm_state(...) and pass comm_state="
        )
    if comm_state is not None and cfg is None:
        raise ValueError("comm_state given without compression")
    from apex_tpu.parallel.overlap import is_bucketed_residuals

    bucketed_state = comm_state is not None and is_bucketed_residuals(
        comm_state["residuals"]
    )
    if bucketed_state and not overlap_grad_sync:
        raise ValueError(
            "comm state was built with bucket_bytes= (per-bucket "
            "residuals): pass overlap_grad_sync=True"
        )
    if overlap_grad_sync and comm_state is not None \
            and not bucketed_state:
        raise ValueError(
            "overlap_grad_sync with compression needs a BUCKETED "
            "comm state: build it with init_comm_state(..., "
            "bucket_bytes=<the same bucket size>)"
        )
    if hierarchical:
        dcn_axis, ici_axis = axis_name
        world = _axis_size(dcn_axis) * _axis_size(ici_axis)
    else:
        world = _axis_size(axis_name)

    step = None if comm_state is None else comm_state["step"]

    from apex_tpu.telemetry.spans import phase as _phase

    def sync(g, residual, key):
        # tlm.grad_sync: every collective this reduce issues carries
        # the phase in its HLO metadata, so xprof segments the step's
        # comm time from its compute (docs/observability.md)
        with _phase("grad_sync"):
            orig_dtype = g.dtype
            if allreduce_always_fp32:
                g = g.astype(jnp.float32)
            if gradient_predivide_factor != 1.0:
                g = g / gradient_predivide_factor
            if hierarchical:
                g, new_residual = _hierarchical_psum(
                    g, dcn_axis, ici_axis, compression=cfg,
                    residual=residual, step=step, key=key,
                )
            else:
                g = jax.lax.psum(g, axis_name)
                new_residual = None
            if gradient_average:
                post = world / gradient_predivide_factor
                if post != 1.0:
                    g = g / post
            elif gradient_predivide_factor != 1.0:
                g = g * gradient_predivide_factor
            return g.astype(orig_dtype), new_residual

    from apex_tpu.parallel.overlap import dither_key

    def leaf_key(i):
        return dither_key(cfg, step, i)

    leaves, treedef = jax.tree_util.tree_flatten(grads)

    if overlap_grad_sync:
        from apex_tpu.parallel.overlap import (
            DEFAULT_BUCKET_BYTES,
            GradientBuckets,
            reduce_bucketed,
        )

        plan = GradientBuckets.for_tree(
            grads,
            DEFAULT_BUCKET_BYTES if bucket_bytes is None
            else bucket_bytes,  # 0 reaches the >=1 validation, not
        )                       # the default
        emit_bucket_comm_events(plan, axis_name, cfg,
                                where="all_reduce_gradients")
        bufs = plan.pack(leaves)
        if comm_state is None:
            out, _ = reduce_bucketed(plan, bufs, cfg, None, None, sync)
            return jax.tree_util.tree_unflatten(
                treedef, plan.unpack(out, leaves)
            )
        _check_bucketed_state(plan, comm_state, cfg, dcn_axis, ici_axis)
        out_bufs, new_residuals = reduce_bucketed(
            plan, bufs, cfg, comm_state["residuals"], step, sync
        )
        return jax.tree_util.tree_unflatten(
            treedef, plan.unpack(out_bufs, leaves)
        ), {"residuals": new_residuals, "step": comm_state["step"] + 1}

    if comm_state is None:
        out = [sync(g, None, None)[0] for g in leaves]
        return jax.tree_util.tree_unflatten(treedef, out)
    residuals = treedef.flatten_up_to(comm_state["residuals"])
    use_ef = cfg.error_feedback
    synced = [
        sync(g, r if use_ef else None, leaf_key(i))
        for i, (g, r) in enumerate(zip(leaves, residuals))
    ]
    new_state = {
        # error_feedback=False: the state only feeds the step counter,
        # residuals pass through untouched
        "residuals": jax.tree_util.tree_unflatten(
            treedef, [r for _, r in synced]
        ) if use_ef else comm_state["residuals"],
        "step": comm_state["step"] + 1,
    }
    return jax.tree_util.tree_unflatten(
        treedef, [g for g, _ in synced]
    ), new_state


def emit_bucket_comm_events(plan, axis_name, cfg, where: str) -> None:
    """Trace-time telemetry for a bucketed reduce: one ``comm_bucket``
    event per bucket, carrying per-leg bytes-on-wire ESTIMATES under
    the ring model (:func:`apex_tpu.telemetry.events.ring_wire_bytes`
    — the same formulas ``tools/comm_audit.py`` applies to parsed HLO;
    the audit's measured JSON stays the ground truth, these events are
    the live stream's cheap approximation of it).

    Fires while the step is being TRACED — once per compile, with every
    field a static host int — so the compiled program and the step's
    wall time are untouched.  Free when no telemetry sink is
    registered."""
    if not _events.have_sinks():
        return
    from apex_tpu.telemetry.events import ring_wire_bytes

    hierarchical = isinstance(axis_name, (tuple, list))
    if hierarchical:
        dcn_axis, ici_axis = axis_name
        dcn, ici = _axis_size(dcn_axis), _axis_size(ici_axis)
    else:
        world = _axis_size(axis_name)
    for name, b in zip(plan.names, plan.buckets):
        itemsize = int(np.dtype(b.dtype).itemsize)
        fields = {
            "where": where,
            "bucket": name,
            "elements": int(b.size),
            "dtype": str(np.dtype(b.dtype).name),
            "bytes": int(b.size) * itemsize,
            "compression": (cfg.method if cfg is not None else "none"),
        }
        if hierarchical:
            # the reduce's actual decomposition: RS(ici) -> AR(dcn,
            # int8-quantized when compressed) -> AG(ici), over the
            # ici-padded flat buffer (see _hierarchical_psum)
            padded = b.size + (-b.size) % ici
            chunk = padded // ici
            padded_bytes = padded * itemsize
            if cfg is None:
                ar_payload = chunk * itemsize
            else:
                # int8 values + one fp32 scale per block (block-padded)
                qpad = chunk + (-chunk) % cfg.block_size
                ar_payload = qpad + (qpad // cfg.block_size) * 4
            if cfg is not None and cfg.ici_legs:
                # int8 legs: values at 1 byte + the per-row scale
                # sidecar (one fp32 scale per block of each rank's
                # chunk — quantize_rows keeps blocks inside chunks)
                nb = max(-(-chunk // cfg.block_size), 1)
                leg_payload = padded + ici * nb * 4
                rs_bytes, ag_bytes = leg_payload, leg_payload
            else:
                rs_bytes, ag_bytes = padded_bytes, padded_bytes
            fields.update(
                dcn_size=int(dcn), ici_size=int(ici),
                ici_compressed=bool(cfg is not None and cfg.ici_legs),
                rs_ici_wire_bytes=round(
                    ring_wire_bytes("reduce-scatter", ici, rs_bytes)),
                ar_dcn_wire_bytes=round(
                    ring_wire_bytes("all-reduce", dcn, ar_payload)),
                ag_ici_wire_bytes=round(
                    ring_wire_bytes("all-gather", ici, ag_bytes,
                                    result_bytes=ag_bytes)),
            )
        else:
            fields.update(
                world_size=int(world),
                ar_wire_bytes=round(
                    ring_wire_bytes("all-reduce", world,
                                    b.size * itemsize)),
            )
        _events.emit("comm_bucket", **fields)


def _check_bucketed_state(plan, comm_state, cfg, dcn_axis,
                          ici_axis) -> None:
    """Fail with an actionable message when the per-bucket residual
    sizes do not match the trace-time bucket plan (the shapes would
    otherwise error deep inside quantized_psum)."""
    from apex_tpu.ops.quantization import hierarchical_residual_sizes

    residuals = comm_state["residuals"]
    if set(residuals) != set(plan.names):
        raise ValueError(
            f"bucketed comm state has {len(residuals)} buckets, the "
            f"grads bucket into {len(plan.buckets)}: init_comm_state "
            "must use the same bucket_bytes and see the same leaf "
            "shapes/dtypes as the reduce"
        )
    if not cfg.error_feedback:
        return
    dcn, ici = _axis_size(dcn_axis), _axis_size(ici_axis)
    for name, b in zip(plan.names, plan.buckets):
        sizes = hierarchical_residual_sizes(
            b.size, dcn, ici, cfg.block_size, cfg.ici_legs
        )
        if set(sizes) != set(residuals[name]):
            raise ValueError(
                f"residual '{name}' has keys "
                f"{sorted(residuals[name])}, this compression config "
                f"needs {sorted(sizes)}: the comm state was built for "
                "a different config (ici_legs?) — rebuild with "
                "init_comm_state"
            )
        push = residuals[name]["push"]
        if push.size != sizes["push"]:
            raise ValueError(
                f"residual '{name}' has {push.size} elements, the "
                f"bucket's padded chunk is {sizes['push']}: "
                "init_comm_state must use the same bucket_bytes and "
                "leaf dtypes as the reduce"
            )


def init_comm_state(
    tree: Any,
    axis_name: Tuple[str, str],
    compression: Any = "int8",
    mesh: Optional[Mesh] = None,
    param_specs: Any = None,
    bucket_bytes: Optional[int] = None,
    buckets: Any = None,
) -> dict:
    """Zero error-feedback state for compressed hierarchical reduces of
    a grad pytree shaped like ``tree``.

    With ``bucket_bytes`` (or a prebuilt ``buckets`` plan) the state is
    sized for the BUCKETED reduce (``overlap_grad_sync=True``): one
    push/pull residual pair per bucket instead of per leaf, keyed
    ``bucket_000``... — pass the SAME bucket size the reduce will use
    (and, for model-sharded params, the same ``param_specs``) so the
    host-built plan matches the trace-time one.

    Residuals are sized from the PER-DEVICE gradient shapes the reduce
    will see inside shard_map.  For the usual DDP setup (replicated
    params, per-device grads of the same shape) that is simply the
    params pytree; params sharded over MODEL axes (pp/tp stacks) have
    smaller per-device leaves — pass their ``param_specs`` so the
    host-side path can divide each dimension by the mesh axes that
    shard it.

    With ``mesh`` given this runs host-side and returns GLOBAL arrays
    (place them with :func:`comm_state_specs`); without it, it must run
    inside ``shard_map`` (axis sizes come from the bound axes, leaf
    shapes are already local) and returns the per-device residuals
    directly.  The state is ordinary checkpointable data: save/restore
    it with the training state so a resumed run keeps its compensation
    instead of restarting the quantization bias from zero."""
    from apex_tpu.ops.quantization import (
        as_compression_config,
        hierarchical_residual_sizes,
    )

    cfg = as_compression_config(compression)
    if cfg is None:
        raise ValueError("init_comm_state needs a compression config")
    if bucket_bytes is not None or buckets is not None:
        from apex_tpu.parallel.overlap import (
            GradientBuckets,
            bucket_comm_state,
        )

        plan = buckets or GradientBuckets.for_tree(
            tree, bucket_bytes, param_specs=param_specs, mesh=mesh
        )
        return bucket_comm_state(plan, axis_name, cfg, mesh=mesh)
    dcn_axis, ici_axis = axis_name
    if mesh is not None:
        dcn, ici = mesh.shape[dcn_axis], mesh.shape[ici_axis]
        replicas = dcn * ici
    else:
        dcn, ici = _axis_size(dcn_axis), _axis_size(ici_axis)
        replicas = 1

    def local_size(leaf, spec) -> int:
        # the ONE per-device-shape derivation, shared with the bucket
        # plan builder so bucketed and per-leaf residual sizing can
        # never disagree about what "local" means
        from apex_tpu.parallel.overlap import _local_shape

        n = 1
        for d in _local_shape(leaf, spec, mesh):
            n *= int(d)
        return n

    def one(leaf, spec):
        sizes = hierarchical_residual_sizes(
            local_size(leaf, spec), dcn, ici, cfg.block_size,
            cfg.ici_legs,
        )
        # a leaf sharded over MODEL axes (pp/tp stacks) carries a
        # DISTINCT residual per model-axis position as well — the
        # global buffer must hold every one of them
        reps = replicas * _model_axis_extent(spec, mesh)
        return {
            k: jnp.zeros((reps * n,), jnp.float32)
            for k, n in sizes.items()
        }

    if param_specs is None:
        residuals = jax.tree.map(lambda l: one(l, None), tree)
    else:
        residuals = jax.tree.map(one, tree, param_specs)
    return {
        "residuals": residuals,
        "step": jnp.zeros((), jnp.int32),
    }


def _model_axis_extent(spec, mesh: Optional[Mesh]) -> int:
    """Product of the mesh-axis sizes a leaf's spec shards it over."""
    if spec is None or mesh is None:
        return 1
    from apex_tpu.transformer.parallel_state import spec_axis_names

    extent = 1
    for ax in spec_axis_names(spec):
        extent *= mesh.shape[ax]
    return extent


def comm_state_specs(comm_state: dict,
                     axis_name: Tuple[str, str],
                     param_specs: Any = None,
                     buckets: Any = None) -> dict:
    """shard_map / device_put specs for :func:`init_comm_state` output:
    residuals are device-varying over both data axes (sharded along
    axis 0), the step counter is replicated.

    Pass the same ``param_specs`` given to :func:`init_comm_state` when
    params are sharded over model axes: a pp/tp-sharded leaf's residual
    varies over those axes too, and declaring it replicated there would
    be rejected (or silently wrong) under shard_map.  For BUCKETED
    state over model-sharded params, pass the ``buckets`` plan (built
    with the same ``param_specs``/``mesh``) instead — each bucket's
    residual varies over the union of its member leaves' model axes."""
    from apex_tpu.parallel.overlap import is_bucketed_residuals

    dcn_axis, ici_axis = axis_name
    if is_bucketed_residuals(comm_state.get("residuals")):
        if buckets is not None:
            rs = {
                name: {
                    # key set follows the state (push/pull, plus the
                    # ici_push/ici_pull pair when ici_legs sized them)
                    k: P((dcn_axis, ici_axis, *b.model_axes))
                    for k in comm_state["residuals"][name]
                }
                for name, b in zip(buckets.names, buckets.buckets)
            }
        elif param_specs is not None:
            # silently emitting P((dcn, ici)) here would mis-shard
            # residuals whose buckets were sized with model-axis reps
            raise ValueError(
                "bucketed comm state over model-sharded params needs "
                "the bucket plan to spec each bucket's model axes: "
                "pass buckets=GradientBuckets.for_tree(params, "
                "bucket_bytes, param_specs=..., mesh=...) — the same "
                "plan init_comm_state used"
            )
        else:
            rs = jax.tree.map(
                lambda _: P((dcn_axis, ici_axis)),
                comm_state["residuals"],
            )
        return {"residuals": rs, "step": P()}
    if param_specs is None:
        specs = jax.tree.map(
            lambda _: P((dcn_axis, ici_axis)), comm_state
        )
        specs["step"] = P()
        return specs

    from apex_tpu.transformer.parallel_state import spec_axis_names

    def leaf_spec(spec, res):
        axes = (dcn_axis, ici_axis, *spec_axis_names(spec))
        return {k: P(axes) for k in res}

    return {
        "residuals": jax.tree.map(
            leaf_spec, param_specs, comm_state["residuals"],
            is_leaf=lambda x: isinstance(x, P),
        ),
        "step": P(),
    }


class DistributedDataParallel:
    """Configuration object for DP gradient sync.

    Use either as a callable on a grad pytree inside an SPMD context::

        ddp = DistributedDataParallel(axis_name="dp")
        grads = ddp(grads)          # inside shard_map

    or let it build the whole sharded value-and-grad for you::

        grad_fn = ddp.value_and_grad(loss_fn, mesh)
        (loss, grads) = grad_fn(params, batch)   # batch sharded over dp

    The constructor knobs mirror the reference's
    (reference: apex/parallel/distributed.py:139-206).  The reference's
    ``message_size``/stream knobs map to ``overlap_grad_sync=True`` +
    ``bucket_bytes`` (bucketed reduces the scheduler can overlap — see
    :mod:`apex_tpu.parallel.overlap`); the legacy spellings are still
    accepted-and-ignored for source compatibility.

    ``compression`` (with a hierarchical ``axis_name=(dcn, ici)``
    pair) quantizes the DCN leg of the reduce to int8; with error
    feedback (the default) build residual state once with
    :meth:`init_comm_state` and call ``ddp(grads, comm_state)``, which
    then returns ``(grads, new_comm_state)``.
    """

    def __init__(
        self,
        axis_name: str = "dp",
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        allreduce_always_fp32: bool = False,
        compression: Any = None,
        overlap_grad_sync: bool = False,
        bucket_bytes: Optional[int] = None,
        # accepted for source compat; meaningless under XLA:
        message_size: int = 10000000,
        delay_allreduce: bool = False,
        num_allreduce_streams: int = 1,
        retain_allreduce_buffers: bool = False,
    ):
        from apex_tpu.ops.quantization import as_compression_config
        from apex_tpu.parallel.overlap import DEFAULT_BUCKET_BYTES

        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.compression = as_compression_config(compression)
        self.overlap_grad_sync = overlap_grad_sync
        self.bucket_bytes = (DEFAULT_BUCKET_BYTES if bucket_bytes is None
                             else bucket_bytes)
        if self.bucket_bytes < 1:
            raise ValueError("bucket_bytes must be >= 1")
        if self.compression is not None and not isinstance(
            axis_name, (tuple, list)
        ):
            raise ValueError(
                "compression quantizes the DCN leg of a hierarchical "
                "reduce: pass axis_name=(dcn_axis, ici_axis)"
            )

    def __call__(self, grads: Any,
                 comm_state: Optional[dict] = None) -> Any:
        return all_reduce_gradients(
            grads,
            axis_name=self.axis_name,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            allreduce_always_fp32=self.allreduce_always_fp32,
            compression=self.compression,
            comm_state=comm_state,
            overlap_grad_sync=self.overlap_grad_sync,
            bucket_bytes=self.bucket_bytes,
        )

    def init_comm_state(self, params: Any,
                        mesh: Optional[Mesh] = None,
                        param_specs: Any = None) -> dict:
        """Zero error-feedback state for :meth:`__call__` — host-side
        global arrays with ``mesh`` given (place with
        :meth:`comm_state_specs`), per-device inside shard_map
        otherwise.  Pass ``param_specs`` when params are sharded over
        model axes so residuals are sized from per-device shapes.
        With ``overlap_grad_sync`` the state is bucketed to match, and
        the bucket plan is remembered so :meth:`comm_state_specs` can
        emit per-bucket model-axis specs without the caller rebuilding
        it."""
        if self.overlap_grad_sync:
            from apex_tpu.parallel.overlap import GradientBuckets

            self._bucket_plan = GradientBuckets.for_tree(
                params, self.bucket_bytes, param_specs=param_specs,
                mesh=mesh,
            )
            return init_comm_state(
                params, self.axis_name, self.compression, mesh=mesh,
                param_specs=param_specs, buckets=self._bucket_plan,
            )
        return init_comm_state(
            params, self.axis_name, self.compression, mesh=mesh,
            param_specs=param_specs,
        )

    def comm_state_specs(self, comm_state: dict,
                         param_specs: Any = None,
                         buckets: Any = None) -> dict:
        return comm_state_specs(
            comm_state, self.axis_name, param_specs=param_specs,
            buckets=buckets or getattr(self, "_bucket_plan", None),
        )

    def value_and_grad(
        self,
        loss_fn: Callable,
        mesh: Mesh,
        has_aux: bool = False,
    ) -> Callable:
        """Build ``(params, batch) -> (loss, grads)`` with params replicated,
        batch sharded over ``axis_name``, and grads synced."""
        from jax.sharding import PartitionSpec as P

        shard_map = jax.shard_map

        axis = self.axis_name

        def local_step(params, batch):
            out, grads = jax.value_and_grad(loss_fn, has_aux=has_aux)(
                params, batch
            )
            grads = self(grads)
            if has_aux:
                loss, aux = out
                return jax.lax.pmean(loss, axis), aux, grads
            return jax.lax.pmean(out, axis), grads

        batch_spec = P(axis)
        rep = P()
        out_specs = (rep, rep, rep) if has_aux else (rep, rep)
        return jax.jit(
            shard_map(
                local_step,
                mesh=mesh,
                in_specs=(rep, batch_spec),
                out_specs=out_specs,
                check_vma=False,
            )
        )


class Reducer:
    """Deferred, user-triggered gradient reduction — the functional
    analog of the reference's manual-control DDP alternative
    (reference: apex/parallel/distributed.py:89-126, whose point is
    that unlike DDP nothing syncs during backward; the user calls
    ``reduce()`` when ready, e.g. every K accumulation steps).

    Usage inside a shard_map'd step::

        red = Reducer(axis_name="dp")             # static config
        acc = red.init(params)                    # zeros pytree
        w_local = jax.lax.pcast(params, "dp", to="varying")  # see below
        for k in ...:                             # K times, NO collective
            acc = red.accumulate(acc, jax.grad(local_loss)(w_local, mb[k]))
        mean_grads, acc = red.reduce(acc)         # ONE psum-mean + reset

    The varying-cast is load-bearing: under shard_map, differentiating a
    device-LOCAL (varying) loss with respect to REPLICATED params makes
    JAX insert the reduction itself (the transpose of the replicated→
    varying broadcast is a psum), so "the local gradient before
    reduction" would not exist to defer.  Marking the params varying
    first keeps the per-device gradients local until ``reduce`` — which
    is the entire point of the reference's Reducer (delaying the
    allreduce across accumulation steps).

    Scaling semantics — a DELIBERATE DEVIATION from the reference: the
    reference's Reducer averages only over the world size
    (apex/parallel/distributed.py), returning the SUM over the K
    locally accumulated microbatches.  Here ``gradient_average=True``
    (default) also divides by K, yielding the mean gradient over
    (axis world x K local steps) — so the effective learning rate does
    not silently scale with the accumulation count.  Pass
    ``average_over_microbatches=False`` to reproduce the reference
    scaling exactly (mean over world, sum over K — what you want when
    porting a reference training recipe whose lr schedule was tuned
    against that convention); with ``gradient_average=False`` both
    flags yield the raw sum over both.  ``allreduce_always_fp32`` is
    accepted for signature parity but meaningless here — the
    accumulator is ALWAYS fp32 (see :meth:`init`), so the reduction
    already runs in fp32 regardless.

    ``overlap_grad_sync=True`` switches to the PIPELINED
    accumulate-and-reduce loop (:mod:`apex_tpu.parallel.overlap`): the
    state carries the last microbatch's gradients bucketed but
    un-reduced (``state["pending"]``), and each ``accumulate`` issues
    the previous microbatch's per-bucket reduces — independent of the
    new microbatch's fwd/bwd, so the scheduler overlaps them —
    accumulating the REDUCED sums; ``reduce()`` flushes the final
    pending microbatch and applies the scaling.  Semantics: the result
    is ``Σ_k psum(g_k)`` scaled exactly as the deferred
    ``psum(Σ_k g_k)`` would be — the same mean, a different (per-
    microbatch) summation order, bit-identical to the deferred path at
    K=1 and within accumulation rounding for K>1.  Each microbatch's
    reduce costs wire bytes, so K microbatches move K× the deferred
    mode's bytes — the reference DDP's own default trade (latency
    hidden, bytes multiplied); ``compression="int8"`` composes, with
    per-bucket error-feedback residuals updated every microbatch.  The
    state stays an ordinary pytree: prime it with one ``accumulate``
    and the rest of the loop can be a ``lax.scan``.
    """

    def __init__(
        self,
        axis_name: Any = "dp",
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        allreduce_always_fp32: bool = False,
        average_over_microbatches: bool = True,
        compression: Any = None,
        overlap_grad_sync: bool = False,
        bucket_bytes: Optional[int] = None,
    ):
        from apex_tpu.ops.quantization import as_compression_config
        from apex_tpu.parallel.overlap import DEFAULT_BUCKET_BYTES

        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.average_over_microbatches = average_over_microbatches
        # quantize the DCN leg of the reduce (hierarchical axis pairs
        # only); the error-feedback residual rides the accumulator
        # state dict as state["comm"] and PERSISTS across reduce()
        # cycles — only "sum"/"count" reset
        self.compression = as_compression_config(compression)
        self.overlap_grad_sync = overlap_grad_sync
        self.bucket_bytes = (DEFAULT_BUCKET_BYTES if bucket_bytes is None
                             else bucket_bytes)
        if self.bucket_bytes < 1:
            raise ValueError("bucket_bytes must be >= 1")
        if self.compression is not None and not isinstance(
            axis_name, (tuple, list)
        ):
            raise ValueError(
                "compression quantizes the DCN leg of a hierarchical "
                "reduce: pass axis_name=(dcn_axis, ici_axis)"
            )

    def _needs_comm_state(self) -> bool:
        return self.compression is not None and (
            self.compression.error_feedback
            or self.compression.rounding == "stochastic"
        )

    def init(self, params: Any) -> dict:
        """Zero accumulator state (fp32 buffers — accumulation across
        microbatches in bf16 loses low-order contributions).  With
        compression + error feedback the state also carries the
        quantization residuals (``"comm"``, per BUCKET in overlap
        mode); init must then run inside shard_map (residual shapes
        come from the bound axis sizes)."""
        state = {
            "sum": jax.tree.map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
            ),
            "count": jnp.zeros((), jnp.int32),
        }
        if self._needs_comm_state():
            if self.overlap_grad_sync:
                from apex_tpu.parallel.overlap import (
                    GradientBuckets,
                    bucket_comm_state,
                )

                plan = GradientBuckets.for_tree(
                    params, self.bucket_bytes, dtype=jnp.float32
                )
                state["comm"] = bucket_comm_state(
                    plan, self.axis_name, self.compression
                )
            else:
                state["comm"] = init_comm_state(
                    params, self.axis_name, self.compression
                )
        return state

    def accumulate(self, state: dict, grads: Any) -> dict:
        """Add one microbatch's grads.  Deferred mode: a local add, no
        collective.  Overlap mode: the PREVIOUS microbatch's buckets
        are reduced here (their collectives and this microbatch's
        fwd/bwd are mutually independent — the scheduler's overlap
        window) and the new grads become the in-flight ``pending``."""
        if not self.overlap_grad_sync:
            new = {
                "sum": jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32),
                    state["sum"], grads
                ),
                "count": state["count"] + 1,
            }
            if "comm" in state:
                new["comm"] = state["comm"]
            return new
        new = {"count": state["count"] + 1, "sum": state["sum"]}
        if "comm" in state:
            new["comm"] = state["comm"]
        if "pending" in state:
            reduced, new_comm = self._overlap_reduce_once(
                state["pending"], state.get("comm")
            )
            new["sum"] = jax.tree.map(
                lambda a, r: a + r, state["sum"], reduced
            )
            if new_comm is not None:
                new["comm"] = new_comm
        new["pending"] = jax.tree.map(
            lambda g: jnp.asarray(g).astype(jnp.float32), grads
        )
        return new

    def _overlap_reduce_once(self, tree: Any, comm: Optional[dict]):
        """Per-bucket SUM-reduce of one microbatch's fp32 grads:
        predivide, RS(ici) → AR(dcn, compressed) → AG(ici) per bucket
        (plain psum on a flat axis).  Averaging is deferred to
        :meth:`reduce` so the scaling ops match the deferred path's
        exactly."""
        from apex_tpu.parallel.overlap import (
            GradientBuckets,
            reduce_bucketed,
        )

        f = self.gradient_predivide_factor
        cfg = self.compression
        hierarchical = isinstance(self.axis_name, (tuple, list))
        plan = GradientBuckets.for_tree(
            tree, self.bucket_bytes, dtype=jnp.float32
        )
        emit_bucket_comm_events(plan, self.axis_name, cfg,
                                where="reducer")
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        bufs = plan.pack(leaves)
        step = None if comm is None else comm["step"]

        from apex_tpu.telemetry.spans import phase as _phase

        def reduce_one(buf, residual, key):
            with _phase("grad_sync"):
                if f != 1.0:
                    buf = buf / f
                if hierarchical:
                    dcn_axis, ici_axis = self.axis_name
                    return _hierarchical_psum(
                        buf, dcn_axis, ici_axis, compression=cfg,
                        residual=residual, step=step, key=key,
                    )
                return jax.lax.psum(buf, self.axis_name), None

        out_bufs, new_residuals = reduce_bucketed(
            plan, bufs, cfg,
            None if comm is None else comm["residuals"], step,
            reduce_one,
        )
        new_comm = None
        if comm is not None:
            new_comm = {"residuals": new_residuals,
                        "step": comm["step"] + 1}
        return jax.tree_util.tree_unflatten(
            treedef, plan.unpack(out_bufs, leaves)
        ), new_comm

    def reduce(self, state: dict) -> tuple:
        """One collective over everything accumulated (deferred mode) or
        the flush of the final in-flight microbatch (overlap mode);
        returns ``(grads, fresh_state)`` — the mean over (world x
        count) when ``gradient_average`` (over world only when
        ``average_over_microbatches=False``, the reference scaling),
        the raw sum otherwise."""
        if self.overlap_grad_sync:
            return self._overlap_reduce(state)
        if self.gradient_average and self.average_over_microbatches:
            n = jnp.maximum(state["count"], 1).astype(jnp.float32)
            grads = jax.tree.map(lambda a: a / n, state["sum"])
        else:
            grads = state["sum"]
        comm = state.get("comm")
        out = all_reduce_gradients(
            grads,
            axis_name=self.axis_name,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            allreduce_always_fp32=self.allreduce_always_fp32,
            compression=self.compression,
            comm_state=comm,
        )
        fresh = {
            "sum": jax.tree.map(jnp.zeros_like, state["sum"]),
            "count": jnp.zeros((), jnp.int32),
        }
        if comm is not None:
            grads, fresh["comm"] = out
        else:
            grads = out
        return grads, fresh

    def _overlap_reduce(self, state: dict) -> tuple:
        comm = state.get("comm")
        done = state["sum"]
        if "pending" in state:
            # the final microbatch's reduce — the one round with no
            # following compute to hide behind (same as the reference
            # DDP's trailing bucket)
            reduced, comm = self._overlap_reduce_once(
                state["pending"], comm
            )
            done = jax.tree.map(lambda a, r: a + r, done, reduced)
        if isinstance(self.axis_name, (tuple, list)):
            world = 1
            for ax in self.axis_name:
                world *= _axis_size(ax)
        else:
            world = _axis_size(self.axis_name)
        # the exact scaling ops of the deferred path (sync()'s post
        # divide, then the microbatch mean), so K=1 is bit-identical
        if self.gradient_average:
            post = world / self.gradient_predivide_factor
            if post != 1.0:
                done = jax.tree.map(lambda a: a / post, done)
            if self.average_over_microbatches:
                n = jnp.maximum(state["count"], 1).astype(jnp.float32)
                done = jax.tree.map(lambda a: a / n, done)
        elif self.gradient_predivide_factor != 1.0:
            done = jax.tree.map(
                lambda a: a * self.gradient_predivide_factor, done
            )
        fresh = {
            "sum": jax.tree.map(jnp.zeros_like, state["sum"]),
            "count": jnp.zeros((), jnp.int32),
        }
        if comm is not None:
            fresh["comm"] = comm
        return done, fresh

from apex_tpu.utils.platform import (  # noqa: F401
    is_tpu,
    supports_pallas,
    default_implementation,
)

__all__ = ["is_tpu", "supports_pallas", "default_implementation"]

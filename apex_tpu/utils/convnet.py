"""Shared convnet building blocks (NHWC conv, He init) used by the
ResNet family and the contrib bottleneck blocks."""

from __future__ import annotations

import math

import jax
from jax import lax

__all__ = ["conv_nhwc", "he_init"]


def conv_nhwc(x, w, stride: int = 1, padding="SAME"):
    """2-D conv in the TPU-native NHWC/HWIO layout."""
    return lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def he_init(key, shape, dtype):
    """Kaiming-normal init for HWIO conv weights."""
    fan_in = shape[0] * shape[1] * shape[2]
    return math.sqrt(2.0 / fan_in) * jax.random.normal(key, shape, dtype)

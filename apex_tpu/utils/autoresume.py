"""Auto-resume: periodic checkpoints + latest-state recovery.

The reference only stubs this capability — a ``_GLOBAL_AUTORESUME``
placeholder (reference: apex/transformer/pipeline_parallel/utils.py:34)
and overflow skip-steps; actual save/resume lives in example scripts.
Here it is a real subsystem built on :mod:`apex_tpu.checkpoint`:

- :class:`AutoResume` saves the full train state every
  ``interval_steps`` and on SIGTERM (preemption notice), keeps the last
  ``keep`` checkpoints, and resumes from the newest one at startup;
- state is anything pytree-shaped: params, optimizer state, amp
  state-dicts, data-iterator counters.

Resilience semantics (see docs/resilience.md): resume walks back from
the newest checkpoint past corrupt / truncated / incomplete directories
(:func:`apex_tpu.checkpoint.restore_latest_valid`), so the process a
preemption killed mid-write — or a bit-flipped blob — costs one
checkpoint interval, never the run.
"""

from __future__ import annotations

import logging
import os
import shutil
import signal
from typing import Any, Optional, Set, Tuple

from apex_tpu import checkpoint as ckpt
from apex_tpu.telemetry import events as _events

__all__ = ["AutoResume"]

logger = logging.getLogger("apex_tpu.autoresume")


class AutoResume:
    def __init__(
        self,
        root: str,
        interval_steps: int = 1000,
        keep: int = 2,
        install_sigterm_handler: bool = False,
    ):
        if keep < 1:
            # keep=0 would let _gc delete the checkpoint it just wrote
            raise ValueError(f"keep must be >= 1, got {keep}")
        if interval_steps < 1:
            raise ValueError(
                f"interval_steps must be >= 1, got {interval_steps}"
            )
        self.root = root
        self.interval_steps = interval_steps
        self.keep = keep
        self._termination_requested = False
        self._termination_save_done = False
        # steps known to hold a valid checkpoint: every step this
        # process saved or verified.  Lets _gc be validity-aware
        # without re-checksumming every kept checkpoint on every save.
        self._known_valid: Set[int] = set()
        self._prev_sigterm = None
        if install_sigterm_handler:
            self._prev_sigterm = signal.signal(
                signal.SIGTERM, self._on_sigterm
            )

    # ------------------------------------------------------------ resume
    def resume(self, target: Optional[Any] = None) -> Tuple[Optional[Any], int]:
        """Returns (state, step) of the newest *valid* checkpoint, or
        (None, 0) when starting fresh.

        Corrupt or incomplete step directories (failed
        :func:`apex_tpu.checkpoint.verify`, truncated blob, missing
        files) are logged and skipped — resume walks back until a
        checkpoint both verifies and loads."""
        state, step = ckpt.restore_latest_valid(self.root, target=target)
        if step is None:
            _events.emit("autoresume_fresh", root=self.root)
            return None, 0
        self._known_valid.add(step)
        _events.emit("autoresume_resume", root=self.root, step=step)
        return state, step

    # -------------------------------------------------------------- save
    def _step_is_valid(self, step: int, path: str, deep: bool) -> bool:
        """Whether a step dir may count toward ``keep``.  Raises
        ``OSError`` on a transient read failure (missing files still
        read as invalid) — the caller must not destroy a checkpoint it
        could not actually inspect."""
        if step in self._known_valid:
            return True
        bad = ckpt.verify(path, deep=deep, raise_transient=True)
        if bad:
            return False
        self._known_valid.add(step)
        return True

    def _gc(self, just_saved: Optional[int] = None) -> None:
        """Keep the ``keep`` newest *valid* checkpoints; remove the rest.

        Validity-aware so resuming past corrupt newer steps can never
        end with GC deleting the valid checkpoint it just wrote in
        favor of corrupt higher-numbered dirs: corrupt dirs don't count
        toward ``keep`` and are themselves removed (a visible step dir
        failing :func:`apex_tpu.checkpoint.verify` is genuinely corrupt
        — in-flight writers are ``.tmp`` husks, which
        ``ckpt._steps_desc`` already excludes).  ``just_saved`` is kept
        unconditionally.

        Cost control: dirs NEWER than ``just_saved`` (the dangerous
        case — exactly what a fallback past corrupt steps leaves
        behind, and normally none exist) get the full checksum verify;
        older uncached dirs get the stat-level check (``deep=False``),
        so the save path never streams multi-GB blobs.  A transient
        read error during verification leaves the dir in place,
        uncounted — one storage blip must not delete a healthy
        checkpoint."""
        kept = 0
        for step in ckpt._steps_desc(self.root):
            path = os.path.join(self.root, f"step_{step}")
            if kept < self.keep:
                deep = just_saved is None or step > just_saved
                try:
                    valid = self._step_is_valid(step, path, deep)
                except OSError as e:
                    logger.warning(
                        "cannot verify checkpoint %s (%s); leaving it "
                        "in place unjudged", path, e,
                    )
                    continue  # retained, but does not count toward keep
                if valid:
                    kept += 1
                    continue
                logger.warning(
                    "autoresume GC removing corrupt checkpoint %s", path
                )
                _events.emit("autoresume_gc", step=step, corrupt=True)
            elif step == just_saved:  # invariant backstop: never delete it
                kept += 1
                continue
            else:
                _events.emit("autoresume_gc", step=step, corrupt=False)
            shutil.rmtree(path, ignore_errors=True)
            self._known_valid.discard(step)

    def maybe_save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save when the interval elapses or termination was requested.
        Returns True if a checkpoint was written.

        A termination request triggers exactly ONE forced save (the
        flag is consumed once its checkpoint lands); subsequent steps
        fall back to the normal interval schedule instead of re-saving
        and GC-churning every step.  ``termination_requested()`` keeps
        reporting True so the loop still exits at its boundary."""
        termination_due = (
            self._termination_requested and not self._termination_save_done
        )
        due = force or termination_due or (
            step > 0 and step % self.interval_steps == 0
        )
        if not due:
            return False
        ckpt.save_step(self.root, step, state)
        self._known_valid.add(step)
        if termination_due:
            self._termination_save_done = True
        self._gc(just_saved=step)
        return True

    # ---------------------------------------------------------- discard
    def discard_step(self, step: int) -> None:
        """Quarantine one step directory (e.g. a checksum-valid snapshot
        of an already-diverged state that rollback must not resume
        into): renamed to ``step_<N>.discarded`` (``.discarded.<k>`` if
        that name is taken — a repeated divergence at the same step
        must not overwrite the earlier forensic copy), which resume/GC
        never see, rather than deleted — training history stays on disk
        for forensics even if every checkpoint turns out to be
        poisoned."""
        src = os.path.join(self.root, f"step_{step}")
        dst = src + ".discarded"
        k = 1
        while os.path.exists(dst):
            dst = src + f".discarded.{k}"
            k += 1
        try:
            os.rename(src, dst)
        except FileNotFoundError:
            pass
        self._known_valid.discard(step)

    def discard_steps_after(self, step: int) -> None:
        """Quarantine every step directory numbered above ``step``,
        making a rollback durable: a crash right after it resumes from
        ``step`` (or older), not from a stale newer checkpoint, and
        later saves at lower step numbers are never GC'd in favor of
        those dirs."""
        for s in ckpt._steps_desc(self.root):
            if s > step:
                logger.warning(
                    "discarding checkpoint step_%d (newer than rollback "
                    "point %d)", s, step,
                )
                self.discard_step(s)

    # --------------------------------------------------- failure signal
    def _on_sigterm(self, signum, frame):
        # mark only; the training loop saves at the next step boundary
        # (async-safe: no I/O in the handler)
        self._termination_requested = True
        self._termination_save_done = False
        prev = self._prev_sigterm
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            # chain: whoever installed a handler before us (cluster
            # agent, profiler flusher) still gets the notice
            prev(signum, frame)

    def termination_requested(self) -> bool:
        """(the reference's AutoResume.termination_requested() shape,
        as used by Megatron-style training loops)"""
        return self._termination_requested

    def request_termination(self) -> None:
        self._termination_requested = True
        self._termination_save_done = False

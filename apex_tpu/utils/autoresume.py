"""Auto-resume: periodic checkpoints + latest-state recovery.

The reference only stubs this capability — a ``_GLOBAL_AUTORESUME``
placeholder (reference: apex/transformer/pipeline_parallel/utils.py:34)
and overflow skip-steps; actual save/resume lives in example scripts.
Here it is a real subsystem built on :mod:`apex_tpu.checkpoint`:

- :class:`AutoResume` saves the full train state every
  ``interval_steps`` and on SIGTERM (preemption notice), keeps the last
  ``keep`` checkpoints, and resumes from the newest one at startup;
- state is anything pytree-shaped: params, optimizer state, amp
  state-dicts, data-iterator counters.
"""

from __future__ import annotations

import os
import shutil
import signal
from typing import Any, Optional, Tuple

from apex_tpu import checkpoint as ckpt

__all__ = ["AutoResume"]


class AutoResume:
    def __init__(
        self,
        root: str,
        interval_steps: int = 1000,
        keep: int = 2,
        install_sigterm_handler: bool = False,
    ):
        self.root = root
        self.interval_steps = interval_steps
        self.keep = keep
        self._termination_requested = False
        if install_sigterm_handler:
            signal.signal(signal.SIGTERM, self._on_sigterm)

    # ------------------------------------------------------------ resume
    def resume(self, target: Optional[Any] = None) -> Tuple[Optional[Any], int]:
        """Returns (state, step) of the newest checkpoint, or
        (None, 0) when starting fresh."""
        step = ckpt.latest_step(self.root)
        if step is None:
            return None, 0
        return ckpt.restore_step(self.root, target=target, step=step), step

    # -------------------------------------------------------------- save
    def _gc(self) -> None:
        import re

        # fullmatch, as in checkpoint.latest_step: a crashed atomic
        # writer leaves a step_<N>.tmp husk that must neither crash the
        # int() parse nor count as a checkpoint
        steps = sorted(
            int(m.group(1))
            for d in os.listdir(self.root)
            if (m := re.fullmatch(r"step_(\d+)", d))
        )
        for old in steps[: -self.keep]:
            shutil.rmtree(
                os.path.join(self.root, f"step_{old}"), ignore_errors=True
            )

    def maybe_save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save when the interval elapses or termination was requested.
        Returns True if a checkpoint was written."""
        due = force or self._termination_requested or (
            step > 0 and step % self.interval_steps == 0
        )
        if not due:
            return False
        ckpt.save_step(self.root, step, state)
        self._gc()
        return True

    # --------------------------------------------------- failure signal
    def _on_sigterm(self, signum, frame):
        # mark only; the training loop saves at the next step boundary
        # (async-safe: no I/O in the handler)
        self._termination_requested = True

    def termination_requested(self) -> bool:
        """(the reference's AutoResume.termination_requested() shape,
        as used by Megatron-style training loops)"""
        return self._termination_requested

    def request_termination(self) -> None:
        self._termination_requested = True

"""Auto-resume: periodic checkpoints + latest-state recovery.

The reference only stubs this capability — a ``_GLOBAL_AUTORESUME``
placeholder (reference: apex/transformer/pipeline_parallel/utils.py:34)
and overflow skip-steps; actual save/resume lives in example scripts.
Here it is a real subsystem built on :mod:`apex_tpu.checkpoint`:

- :class:`AutoResume` saves the full train state every
  ``interval_steps`` and on SIGTERM (preemption notice), keeps the last
  ``keep`` checkpoints, and resumes from the newest one at startup;
- state is anything pytree-shaped: params, optimizer state, amp
  state-dicts, data-iterator counters.

Resilience semantics (see docs/resilience.md): resume walks back from
the newest checkpoint past corrupt / truncated / incomplete directories
(:func:`apex_tpu.checkpoint.restore_latest_valid`), so the process a
preemption killed mid-write — or a bit-flipped blob — costs one
checkpoint interval, never the run.
"""

from __future__ import annotations

import os
import shutil
import signal
from typing import Any, Optional, Tuple

from apex_tpu import checkpoint as ckpt

__all__ = ["AutoResume"]


class AutoResume:
    def __init__(
        self,
        root: str,
        interval_steps: int = 1000,
        keep: int = 2,
        install_sigterm_handler: bool = False,
    ):
        if keep < 1:
            # keep=0 would let _gc delete the checkpoint it just wrote
            raise ValueError(f"keep must be >= 1, got {keep}")
        if interval_steps < 1:
            raise ValueError(
                f"interval_steps must be >= 1, got {interval_steps}"
            )
        self.root = root
        self.interval_steps = interval_steps
        self.keep = keep
        self._termination_requested = False
        self._termination_save_done = False
        self._prev_sigterm = None
        if install_sigterm_handler:
            self._prev_sigterm = signal.signal(
                signal.SIGTERM, self._on_sigterm
            )

    # ------------------------------------------------------------ resume
    def resume(self, target: Optional[Any] = None) -> Tuple[Optional[Any], int]:
        """Returns (state, step) of the newest *valid* checkpoint, or
        (None, 0) when starting fresh.

        Corrupt or incomplete step directories (failed
        :func:`apex_tpu.checkpoint.verify`, truncated blob, missing
        files) are logged and skipped — resume walks back until a
        checkpoint both verifies and loads."""
        state, step = ckpt.restore_latest_valid(self.root, target=target)
        if step is None:
            return None, 0
        return state, step

    # -------------------------------------------------------------- save
    def _gc(self) -> None:
        # ckpt._steps_desc excludes .tmp husks from crashed atomic
        # writers, so GC can neither crash on them nor count them
        for old in ckpt._steps_desc(self.root)[self.keep:]:
            shutil.rmtree(
                os.path.join(self.root, f"step_{old}"), ignore_errors=True
            )

    def maybe_save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save when the interval elapses or termination was requested.
        Returns True if a checkpoint was written.

        A termination request triggers exactly ONE forced save (the
        flag is consumed once its checkpoint lands); subsequent steps
        fall back to the normal interval schedule instead of re-saving
        and GC-churning every step.  ``termination_requested()`` keeps
        reporting True so the loop still exits at its boundary."""
        termination_due = (
            self._termination_requested and not self._termination_save_done
        )
        due = force or termination_due or (
            step > 0 and step % self.interval_steps == 0
        )
        if not due:
            return False
        ckpt.save_step(self.root, step, state)
        if termination_due:
            self._termination_save_done = True
        self._gc()
        return True

    # --------------------------------------------------- failure signal
    def _on_sigterm(self, signum, frame):
        # mark only; the training loop saves at the next step boundary
        # (async-safe: no I/O in the handler)
        self._termination_requested = True
        self._termination_save_done = False
        prev = self._prev_sigterm
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            # chain: whoever installed a handler before us (cluster
            # agent, profiler flusher) still gets the notice
            prev(signum, frame)

    def termination_requested(self) -> bool:
        """(the reference's AutoResume.termination_requested() shape,
        as used by Megatron-style training loops)"""
        return self._termination_requested

    def request_termination(self) -> None:
        self._termination_requested = True
        self._termination_save_done = False

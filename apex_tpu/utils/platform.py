"""Backend detection — the analog of the reference's extension-availability
probing (reference: apex/parallel/__init__.py:13-19, apex/amp/scaler.py:66-80):
every fused op here has a Pallas fast path and a pure-XLA fallback, chosen
at trace time.

Detection is stateless and keyed on the *current* default JAX backend.  A
mid-process backend switch is picked up as soon as JAX itself re-resolves
the backend — i.e. after ``jax.extend.backend.clear_backends()`` +
``jax.config.update("jax_platforms", ...)``, which is exactly what
``__graft_entry__._force_cpu_platform`` performs (a bare config update
without clearing leaves JAX's own backend cache, and therefore this module,
on the old platform).  The env override ``APEX_TPU_DISABLE_PALLAS`` is
honored per call.
"""

from __future__ import annotations

import os

__all__ = ["is_tpu", "supports_pallas", "default_implementation"]

_TPU_PLATFORMS = ("tpu", "axon")


def _current_platform() -> str:
    try:
        import jax

        # cached inside JAX; re-resolves once clear_backends() has run
        return jax.default_backend().lower()
    except Exception:
        return "unknown"


def is_tpu() -> bool:
    return _current_platform() in _TPU_PLATFORMS


def supports_pallas() -> bool:
    """Whether Pallas TPU kernels can compile on the current backend."""
    if os.environ.get("APEX_TPU_DISABLE_PALLAS"):
        return False
    return is_tpu()


def default_implementation() -> str:
    return "pallas" if supports_pallas() else "xla"

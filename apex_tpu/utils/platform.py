"""Backend detection — the analog of the reference's extension-availability
probing (reference: apex/parallel/__init__.py:13-19, apex/amp/scaler.py:66-80):
every fused op here has a Pallas fast path and a pure-XLA fallback, chosen
at trace time.
"""

from __future__ import annotations

import functools
import os

__all__ = ["is_tpu", "supports_pallas", "default_implementation"]

_TPU_PLATFORMS = ("tpu", "axon")


@functools.lru_cache(maxsize=1)
def is_tpu() -> bool:
    try:
        import jax

        return jax.devices()[0].platform.lower() in _TPU_PLATFORMS
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def supports_pallas() -> bool:
    """Whether Pallas TPU kernels can compile on the current backend."""
    if os.environ.get("APEX_TPU_DISABLE_PALLAS"):
        return False
    return is_tpu()


def default_implementation() -> str:
    return "pallas" if supports_pallas() else "xla"

"""apex_tpu — a TPU-native training-acceleration framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of Apex
(mixed-precision training, fused kernels and optimizers, data/tensor/pipeline
parallelism) for TPU hardware.  Nothing here is a port: the reference's
CUDA streams, monkey-patching and NCCL process groups are replaced by their
idiomatic TPU equivalents — precision *policies* applied at function
boundaries, jit-fused pytree optimizers, Pallas kernels for the hot ops, and
`jax.sharding.Mesh` axes with XLA collectives for every flavour of
parallelism.

Layout (mirrors the reference's component inventory, see SURVEY.md §2):

- :mod:`apex_tpu.amp`            — precision policies O0–O5, dynamic loss scaling
- :mod:`apex_tpu.optimizers`     — fused Adam/LAMB/SGD/NovoGrad/Adagrad (+ mixed-precision LAMB)
- :mod:`apex_tpu.multi_tensor_apply` — whole-pytree scale/axpby/l2norm primitives
- :mod:`apex_tpu.normalization`  — fused LayerNorm (Pallas)
- :mod:`apex_tpu.fused_dense`    — GEMM+bias(+GELU) fused layers
- :mod:`apex_tpu.mlp`            — whole-MLP fused module
- :mod:`apex_tpu.ops`            — Pallas kernels (layernorm, softmax, flash attention, …)
- :mod:`apex_tpu.parallel`       — data-parallel runtime, SyncBatchNorm, LARC
- :mod:`apex_tpu.transformer`    — Megatron-style tensor/pipeline parallel toolkit
- :mod:`apex_tpu.contrib`        — xentropy, ASP sparsity, MHA modules, …
- :mod:`apex_tpu.telemetry`      — runtime metrics (async scalar harvesting), subsystem events, phase traces
- :mod:`apex_tpu.serving`        — inference: paged KV cache, fused sampling, continuous batching
- :mod:`apex_tpu.fleet`          — multi-replica serving: SLO-aware routing, prefix affinity, failover
"""

__version__ = "0.1.0"

import logging as _logging
import os as _os


class RankInfoFormatter(_logging.Formatter):
    """Rank-annotated log formatter.

    TPU-native analog of the reference's rank-aware root logger
    (reference: apex/__init__.py:30-42) — uses the JAX process index
    instead of torch.distributed rank.
    """

    def format(self, record):
        try:
            import jax

            rank = jax.process_index()
            world = jax.process_count()
        except Exception:
            rank, world = 0, 1
        record.rank_info = f"[{rank}/{world}]"
        return super().format(record)


def _install_logger():
    logger = _logging.getLogger("apex_tpu")
    if not logger.handlers:
        handler = _logging.StreamHandler()
        handler.setFormatter(
            RankInfoFormatter(
                "%(asctime)s %(rank_info)s %(name)s %(levelname)s: %(message)s"
            )
        )
        logger.addHandler(handler)
        logger.setLevel(
            _os.environ.get("APEX_TPU_LOG_LEVEL", "WARNING").upper()
        )
    return logger


logger = _install_logger()

from apex_tpu import amp  # noqa: E402
from apex_tpu import multi_tensor_apply  # noqa: E402
from apex_tpu import optimizers  # noqa: E402
from apex_tpu import normalization  # noqa: E402
from apex_tpu import parallel  # noqa: E402
from apex_tpu import fused_dense  # noqa: E402
from apex_tpu import mlp  # noqa: E402
from apex_tpu import fp16_utils  # noqa: E402
from apex_tpu import rnn  # noqa: E402
from apex_tpu import reparameterization  # noqa: E402

# heavier subpackages load lazily: `apex_tpu.transformer`,
# `apex_tpu.models`, `apex_tpu.contrib`, `apex_tpu.ops`,
# `apex_tpu.checkpoint`, `apex_tpu.resilience`, `apex_tpu.telemetry`
# resolve on first attribute access
_LAZY = ("transformer", "models", "contrib", "ops", "checkpoint",
         "resilience", "telemetry", "serving", "fleet")


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f"apex_tpu.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")


__all__ = [
    "amp",
    "multi_tensor_apply",
    "optimizers",
    "normalization",
    "parallel",
    "fused_dense",
    "mlp",
    "fp16_utils",
    "rnn",
    "reparameterization",
    "transformer",
    "models",
    "contrib",
    "ops",
    "checkpoint",
    "resilience",
    "telemetry",
    "serving",
    "fleet",
    "logger",
    "__version__",
]

"""Native runtime bindings — the ``apex_C`` extension analog.

Compiles :file:`apex_c.cpp` on demand with g++ (cached under
``_build/``) and exposes it through ctypes over numpy buffers.  Falls
back to pure-numpy implementations when no toolchain is available, the
same graceful degradation the reference applies when its extensions
aren't built (reference: apex/parallel/distributed.py:13-23).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "flatten",
    "unflatten",
    "plan_buckets",
    "native_available",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        so = os.path.join(_BUILD, "libapex_c.so")
        src = os.path.join(_HERE, "apex_c.cpp")
        try:
            if not os.path.exists(so) or (
                os.path.getmtime(so) < os.path.getmtime(src)
            ):
                os.makedirs(_BUILD, exist_ok=True)
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-pthread", src, "-o", so],
                    check=True, capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(so)
            lib.apex_c_flatten.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32,
            ]
            lib.apex_c_unflatten.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.c_int32,
            ]
            lib.apex_c_plan_buckets.argtypes = [
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ]
            lib.apex_c_plan_buckets.restype = ctypes.c_int64
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def native_available() -> bool:
    return _load() is not None


def _as_contig(arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
    return [np.ascontiguousarray(a) for a in arrays]


def _bytes_view(a: np.ndarray) -> np.ndarray:
    # atleast_1d: a 0-d array cannot be viewed as uint8
    return np.atleast_1d(a).view(np.uint8).reshape(-1)


def flatten(arrays: Sequence[np.ndarray], threads: int = 8) -> np.ndarray:
    """Concatenate host arrays byte-wise into one uint8 buffer
    (reference: ``apex_C.flatten``, csrc/flatten_unflatten.cpp:15)."""
    arrays = _as_contig(arrays)
    nbytes = [a.nbytes for a in arrays]
    out = np.empty(sum(nbytes), np.uint8)
    lib = _load()
    if lib is None or not arrays:
        off = 0
        for a, nb in zip(arrays, nbytes):
            out[off : off + nb] = _bytes_view(a)
            off += nb
        return out
    n = len(arrays)
    srcs = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays]
    )
    sizes = (ctypes.c_int64 * n)(*nbytes)
    lib.apex_c_flatten(
        srcs, sizes, n, out.ctypes.data_as(ctypes.c_void_p), threads
    )
    return out


def unflatten(
    flat: np.ndarray,
    shapes: Sequence[Tuple[int, ...]],
    dtypes: Sequence[np.dtype],
    threads: int = 8,
) -> List[np.ndarray]:
    """Split a flat uint8 buffer back into arrays
    (reference: ``apex_C.unflatten``)."""
    flat = np.ascontiguousarray(flat.view(np.uint8).reshape(-1))
    outs = [np.empty(s, d) for s, d in zip(shapes, dtypes)]
    nbytes = [o.nbytes for o in outs]
    if sum(nbytes) != flat.nbytes:
        raise ValueError(
            f"flat buffer has {flat.nbytes} bytes but shapes/dtypes "
            f"describe {sum(nbytes)}"
        )
    lib = _load()
    if lib is None or not outs:
        off = 0
        for o, nb in zip(outs, nbytes):
            _bytes_view(o)[:] = flat[off : off + nb]
            off += nb
        return outs
    n = len(outs)
    dsts = (ctypes.c_void_p * n)(
        *[o.ctypes.data_as(ctypes.c_void_p).value for o in outs]
    )
    sizes = (ctypes.c_int64 * n)(*nbytes)
    lib.apex_c_unflatten(
        flat.ctypes.data_as(ctypes.c_void_p), dsts, sizes, n, threads
    )
    return outs


def plan_buckets(nbytes: Sequence[int], cap_bytes: int) -> np.ndarray:
    """Greedy size-capped bucket assignment — the host-side analog of
    DDP's bucket-structure discovery (reference:
    apex/parallel/distributed.py:364-395).  Returns int32 bucket ids."""
    n = len(nbytes)
    ids = np.empty(n, np.int32)
    lib = _load()
    if lib is None:
        bucket = used = 0
        for i, nb in enumerate(nbytes):
            if used > 0 and used + nb > cap_bytes:
                bucket += 1
                used = 0
            ids[i] = bucket
            used += nb
        return ids
    arr = (ctypes.c_int64 * n)(*nbytes)
    lib.apex_c_plan_buckets(
        arr, n, cap_bytes, ids.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int32)
        )
    )
    return ids

// apex_tpu native runtime helpers (the `apex_C` extension analog).
//
// Capability match of the reference's C++ runtime pieces:
//  - flatten/unflatten of tensor lists (reference:
//    csrc/flatten_unflatten.cpp:15-17, used by DDP's flat buckets)
//  - the bucket planner behind DDP's first-iteration bucket-structure
//    discovery (reference: apex/parallel/distributed.py:320-409), here a
//    deterministic greedy size-capped planner
//
// Compiled on demand with g++ (no torch/pybind dependency): plain
// C ABI over contiguous host buffers, driven from Python via ctypes.
// The hot paths are parallel memcpy loops — on TPU hosts these feed
// checkpoint serialization and host-side input pipelines, where
// Python-loop copies are the bottleneck the reference also avoided.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Copy n buffers (sizes[i] bytes each) into one contiguous dst.
// Parallelized across `threads` workers over buffer boundaries.
void apex_c_flatten(const void** srcs, const int64_t* nbytes, int64_t n,
                    void* dst, int32_t threads) {
  std::vector<int64_t> offsets(n + 1, 0);
  for (int64_t i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + nbytes[i];
  if (threads < 1) threads = 1;
  auto worker = [&](int32_t w) {
    for (int64_t i = w; i < n; i += threads) {
      std::memcpy(static_cast<char*>(dst) + offsets[i], srcs[i],
                  static_cast<size_t>(nbytes[i]));
    }
  };
  if (threads == 1 || n == 1) {
    worker(0);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads);
  for (int32_t w = 0; w < threads; ++w) pool.emplace_back(worker, w);
  for (auto& t : pool) t.join();
}

// Inverse: split one contiguous src back into n buffers.
void apex_c_unflatten(const void* src, void** dsts, const int64_t* nbytes,
                      int64_t n, int32_t threads) {
  std::vector<int64_t> offsets(n + 1, 0);
  for (int64_t i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + nbytes[i];
  if (threads < 1) threads = 1;
  auto worker = [&](int32_t w) {
    for (int64_t i = w; i < n; i += threads) {
      std::memcpy(dsts[i], static_cast<const char*>(src) + offsets[i],
                  static_cast<size_t>(nbytes[i]));
    }
  };
  if (threads == 1 || n == 1) {
    worker(0);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads);
  for (int32_t w = 0; w < threads; ++w) pool.emplace_back(worker, w);
  for (auto& t : pool) t.join();
}

// Greedy size-capped bucketing: walk tensors in order, start a new
// bucket when adding one would exceed cap_bytes (a lone oversized
// tensor still gets its own bucket).  Writes bucket ids and returns the
// bucket count.
int64_t apex_c_plan_buckets(const int64_t* nbytes, int64_t n,
                            int64_t cap_bytes, int32_t* bucket_ids) {
  int64_t bucket = 0, used = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (used > 0 && used + nbytes[i] > cap_bytes) {
      ++bucket;
      used = 0;
    }
    bucket_ids[i] = static_cast<int32_t>(bucket);
    used += nbytes[i];
  }
  return n > 0 ? bucket + 1 : 0;
}

}  // extern "C"

"""Zero-loss replica failover: the replayable request log.

A fleet replica is one :class:`~apex_tpu.serving.serve.ContinuousBatcher`
— an in-process object whose death (a preempted chip, an injected
fault) takes its device state, its unharvested window, and its queue
with it.  What must NOT die with it is the requests, and the insight is
that a request's whole recoverable state is three host-side values the
router already handles:

- the original :class:`~apex_tpu.serving.serve.Request` (prompt,
  budget, seed),
- the tokens HARVESTED so far (harvest is the commit point — tokens a
  lost window had produced on device are regenerated, not recovered),
- which replica currently holds it.

:class:`RequestLog` records exactly that, updated from
``ContinuousBatcher.progress()`` after every harvest (pure host
mirrors, no device sync).  On replica death the router re-admits every
in-flight entry elsewhere via :func:`resume_request`: the committed
tokens are replayed as a PROMPT SUFFIX and the budget shrinks by their
count.  This is correct because the serving stack's sampling-key
schedule folds the slot key with the ABSOLUTE context length (the draw
after ``L`` context tokens folds ``L`` — ``GPTModel.decode_fns``): a
replayed prefill over ``prompt + emitted`` lands every token at the
position it originally held, so the logits match and the continuation
is token-identical — trivially for greedy, and for seeded sampling
because the next draw folds the same length into the same
``Request.seed`` key.  The ``_dryrun_fleet`` drill gates this
end-to-end: a killed replica's requests all complete elsewhere with
streams identical to an unkilled run.

The contract's preconditions (the router enforces them at admission):

- the request carries a ``seed`` OR the server is greedy — an
  unseeded sampled request draws from a server key fold and is NOT
  replayable;
- ``len(prompt) + max_new_tokens - 1 <= max_prompt_len`` — the replay
  prompt (original + all-but-one emitted token) must fit the prefill
  window of whichever replica inherits it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from apex_tpu.serving.serve import Request

__all__ = ["LogEntry", "RequestLog", "resume_request"]


@dataclasses.dataclass
class LogEntry:
    """One request's replayable state."""

    request: Request            # the ORIGINAL request, never mutated
    slo: str
    replica: str                # current holder
    t_arrive: float
    #: harvested tokens — committed prefix of the output stream
    emitted: List[int] = dataclasses.field(default_factory=list)
    #: tokens already moved into the prompt by past migrations; the
    #: current holder's own progress is appended on top of these
    replayed: List[int] = dataclasses.field(default_factory=list)
    replays: int = 0
    done: bool = False
    reason: Optional[str] = None
    #: first time any committed token was observed (harvest-boundary
    #: accurate) — the fleet-level, arrival-anchored TTFT numerator
    t_first: Optional[float] = None
    t_done: Optional[float] = None
    #: absolute deadline (router clock) and the relative amount it was
    #: armed with — the router re-arms ``deadline_rel`` on a retry or
    #: a journal resume; None = no deadline (the default)
    deadline: Optional[float] = None
    deadline_rel: Optional[float] = None
    deadline_retries: int = 0
    #: ownership transfers that MOVED pages instead of replaying
    #: tokens (disaggregated prefill→decode handoffs) — unlike
    #: ``replays``, a handoff converts nothing to prompt suffix
    handoffs: int = 0


class RequestLog:
    """uid-keyed log of every admitted request's replayable state.

    The router drives it: :meth:`admit` at submission,
    :meth:`record_progress` after each replica harvest,
    :meth:`complete` when a completion surfaces, :meth:`reassign` when
    a migration moves an entry.  All host-side Python — the log's cost
    is a dict update per harvest."""

    def __init__(self):
        self._entries: Dict[Any, LogEntry] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, uid: Any) -> bool:
        return uid in self._entries

    def get(self, uid: Any) -> LogEntry:
        return self._entries[uid]

    def admit(self, request: Request, slo: str, replica: str,
              t_arrive: float) -> LogEntry:
        if request.uid in self._entries:
            raise ValueError(
                f"uid {request.uid!r} is already logged — fleet uids "
                "must be unique across the run")
        e = LogEntry(request=request, slo=slo, replica=replica,
                     t_arrive=float(t_arrive))
        self._entries[request.uid] = e
        return e

    @staticmethod
    def _check_budget(e: "LogEntry") -> None:
        """The log's core invariant: a request never commits more than
        its budget.  The serving loop enforces it per step — one token
        per harvest record in the plain window, and the speculative
        window's device-side cap on multi-token commits
        (``n_commit <= steps_left``) — so a violation here means a
        broken batcher, and the resume math downstream
        (:func:`resume_request`) would turn it into a nonsensical
        negative budget.  Fail at the recording boundary instead, where
        the offending replica is still known."""
        if len(e.emitted) > e.request.max_new_tokens:
            raise ValueError(
                f"uid {e.request.uid!r} over-committed: "
                f"{len(e.emitted)} tokens recorded against a budget of "
                f"{e.request.max_new_tokens} on replica "
                f"{e.replica!r} — a multi-token (speculative) advance "
                "must be capped at the slot's remaining budget")

    def record_progress(self, replica: str,
                        progress: Dict[Any, List[int]],
                        now: float) -> None:
        """Fold one replica's post-harvest ``progress()`` into the log:
        ``emitted`` becomes the migration-committed tokens plus the
        current holder's harvested stream.  ``toks`` may grow by any
        number of tokens between calls — the speculative window
        commits up to k+1 per verify step — the log stores streams,
        not step counts, so multi-token advances need no special
        casing beyond the budget invariant check."""
        for uid, toks in progress.items():
            e = self._entries.get(uid)
            if e is None or e.done or e.replica != replica:
                continue
            e.emitted = e.replayed + list(toks)
            self._check_budget(e)
            if e.emitted and e.t_first is None:
                e.t_first = now

    def complete(self, uid: Any, tokens: List[int], reason: str,
                 now: float) -> LogEntry:
        e = self._entries[uid]
        e.emitted = e.replayed + list(tokens)
        self._check_budget(e)
        if e.emitted and e.t_first is None:
            e.t_first = now
        e.done, e.reason, e.t_done = True, reason, now
        return e

    def reassign(self, uid: Any, replica: str) -> None:
        """Move an entry to a new holder (a migration): the committed
        stream becomes replayed prompt suffix for the re-admission."""
        e = self._entries[uid]
        e.replayed = list(e.emitted)
        e.replica = replica
        e.replays += 1

    def handoff(self, uid: Any, replica: str) -> None:
        """Move an entry to a new holder by PAGE handoff: the KV moved,
        so nothing converts to prompt suffix — ``replayed`` is
        untouched, and the destination's ``progress()`` keeps reporting
        the full post-replay stream (its imported slot is seeded with
        exactly ``emitted[len(replayed):]``).  Contrast
        :meth:`reassign`, the recompute path."""
        e = self._entries[uid]
        e.replica = replica
        e.handoffs += 1

    def entries(self):
        """Every entry, admission order — what the durable journal
        (:class:`~apex_tpu.fleet.journal.RequestJournal.sync`) and the
        deadline sweep iterate."""
        return list(self._entries.values())

    def inflight_on(self, replica: str) -> List[LogEntry]:
        """Entries the named replica holds that have not completed —
        queued and admitted alike (what a death must migrate)."""
        return [e for e in self._entries.values()
                if not e.done and e.replica == replica]

    def pending(self) -> int:
        return sum(1 for e in self._entries.values() if not e.done)


def resume_request(entry: LogEntry) -> Request:
    """The re-admission for a migrated entry: committed tokens become
    prompt suffix, the budget shrinks by their count, uid and seed are
    unchanged.  Absolute positions (and therefore the key-schedule
    folds) match the original run's, so the continuation reproduces the
    stream the dead replica would have produced.

    The math is by token COUNT, not by harvest-record or step count —
    which is what keeps it exact under speculative decoding, where one
    verify step commits a variable number of tokens and the record/step
    ledgers diverge from the stream length.  Token-identity survives
    too: the Gumbel-coupled acceptance rule commits exactly the tokens
    the plain per-position key schedule would draw, so a resumed
    replica re-drafting from a different mid-stream point converges on
    the same stream regardless of how the dead replica's verify-step
    boundaries fell (the speculative kill-drill in
    tests/test_speculative.py pins this)."""
    base = entry.request
    emitted = list(entry.emitted)
    budget = base.max_new_tokens - len(emitted)
    if budget < 1:
        raise ValueError(
            f"uid {base.uid!r} has no budget left to resume "
            f"({len(emitted)}/{base.max_new_tokens} tokens emitted) — "
            "a spent request should have completed, not migrated")
    return Request(uid=base.uid,
                   prompt=list(base.prompt) + emitted,
                   max_new_tokens=budget,
                   seed=base.seed)

"""apex_tpu.fleet — multi-replica serving above the continuous batcher.

The scenario layer of the serving stack ("heavy traffic from millions
of users"): N :class:`~apex_tpu.serving.serve.ContinuousBatcher`
replicas share ONE set of jitted decode step functions (replicated or
a tp-group ``decode_fns(tp=)`` build — either way zero extra
compilations; the router sees batchers, not meshes) behind one
router.  Two modules, one concern each:

- :mod:`~apex_tpu.fleet.router` — the :class:`FleetRouter` and its
  declarative :class:`FleetPolicy`: per-request SLO classes with
  priority queueing and admission control, prefix-affinity routing
  keyed on the prefix cache's cumulative page hashes, least-loaded
  fallback scored from host-mirror load signals (free pages, queue
  depth, live slots — no new host syncs), a round-robin baseline, and
  DISAGGREGATED replica roles (``FleetPolicy.roles``): prefill-role
  replicas ingest prompts and hand decode-ready streams to
  decode-role replicas by moving KV pages
  (``serving.kv_cache.export_pages``/``import_pages``), a journaled
  ownership transfer that keeps streams token-identical.
- :mod:`~apex_tpu.fleet.failover` — the replayable
  :class:`RequestLog` and :func:`resume_request`: every request's
  (prompt, seed, committed tokens) survives its replica, so a killed
  replica's work re-admits elsewhere with emitted tokens replayed as
  prompt suffix — token-identical continuations, zero lost requests.
- :mod:`~apex_tpu.fleet.journal` — the durable, CRC-checked
  write-ahead :class:`RequestJournal` (O_APPEND JSONL) and
  :func:`recover_journal`: the same replayable state persisted to
  disk, so full-PROCESS death recovers via
  ``FleetRouter.resume_from_journal`` — completed streams kept,
  in-flight requests re-admitted token-identically.

``tools/load_gen.py`` generates deterministic bursty traces and
replays them through a router; docs/serving.md ("Fleet tier") is the
guide; the ``_dryrun_fleet`` config and ``tests/test_fleet.py`` gate
the routing win and the failover contract.
"""

_LAZY_ATTRS = {
    "router": "apex_tpu.fleet.router",
    "failover": "apex_tpu.fleet.failover",
    "journal": "apex_tpu.fleet.journal",
    "SLOClass": "apex_tpu.fleet.router",
    "FleetPolicy": "apex_tpu.fleet.router",
    "BrownoutPolicy": "apex_tpu.fleet.router",
    "Replica": "apex_tpu.fleet.router",
    "FleetRouter": "apex_tpu.fleet.router",
    "FleetCompletion": "apex_tpu.fleet.router",
    "INTERACTIVE": "apex_tpu.fleet.router",
    "BATCH": "apex_tpu.fleet.router",
    "LogEntry": "apex_tpu.fleet.failover",
    "RequestLog": "apex_tpu.fleet.failover",
    "resume_request": "apex_tpu.fleet.failover",
    "RequestJournal": "apex_tpu.fleet.journal",
    "JournalRecovery": "apex_tpu.fleet.journal",
    "recover_journal": "apex_tpu.fleet.journal",
}

__all__ = sorted(_LAZY_ATTRS)


def __getattr__(name):
    if name in _LAZY_ATTRS:
        import importlib

        mod = importlib.import_module(_LAZY_ATTRS[name])
        val = (mod if name in ("router", "failover", "journal")
               else getattr(mod, name))
        globals()[name] = val
        return val
    raise AttributeError(
        f"module 'apex_tpu.fleet' has no attribute {name!r}"
    )

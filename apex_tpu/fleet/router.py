"""Fleet-tier serving: one router over N continuous-batching replicas.

The serving stack below this module tops out at one
:class:`~apex_tpu.serving.serve.ContinuousBatcher` — one chip's worth
of users, no notion of a latency class, and a single point of failure.
This module is the scenario layer on top: N batcher replicas (the
SAME jitted ``decode_fns`` step functions drive every replica, each
over its own cache and pools, so the fleet adds ZERO compilations; a
replica may equally be a tp *group* wrapping a ``decode_fns(tp=)``
sharded build — the router never sees the mesh) behind one
:class:`FleetRouter` that decides, per request, WHO serves it and
WHEN.

Everything the router needs already exists as host-side mirrors — the
design rule is **no new host syncs**:

- **routing key**: the prefix cache's cumulative page hash
  (:func:`~apex_tpu.serving.kv_cache.prompt_page_hashes`) — replica-
  independent by construction, so the router hashes a prompt once and
  probes every replica's prefix index read-only
  (``PagedKVCache.match_len``).  Requests sharing a system prompt land
  on the replica whose pages already hold it; prefill chunks the match
  covers are never computed.
- **load score**: free KV pages (``allocator.num_free``), queue depth,
  live slots — the same quantities the batcher exports as the
  ``pages_free`` / ``pages_shared`` / ``live_slots`` / ``queue_depth``
  telemetry gauges.
- **SLO classes**: per-class queues drained in priority order at every
  pump (interactive ahead of batch on the SAME replica — stable sort,
  FIFO within a class) with per-class admission control: a class whose
  fleet-wide queue is at ``max_queue`` REJECTS instead of growing an
  unbounded backlog (``request_rejected`` event; the caller retries or
  sheds).

Policy is ONE declarative object (:class:`FleetPolicy`), not a pile of
flags — the veScale one-consistent-spec discipline: construct it once,
read any routing/admission decision off it.  ``routing="round_robin"``
is the deliberately dumb baseline (ignores affinity, load AND class
priority) the ``_dryrun_fleet`` gate and the bench rows compare
against.

Failover rides the request log (:mod:`apex_tpu.fleet.failover`):
killing a replica between windows — the in-process analog of the
resilience tier's SIGKILL drills, injected via ``Replica.kill()`` /
``Replica.fail_after(windows)`` — re-admits its queued AND in-flight
requests on surviving replicas with committed tokens replayed as
prompt suffix.  Zero requests are lost, and the replayed continuations
are token-identical (greedy or seeded) to an unkilled run.

The fault-tolerance tier layers four more behaviors on the same log,
all deterministic consequences of the token-identity contract:

- **health monitoring**: a pump that raises is a *replica fault*
  (counted, event-emitted; ``FleetPolicy.max_replica_faults``
  consecutive faults quarantine the replica), and a pump slower than
  ``FleetPolicy.pump_timeout_s`` is a *stall* (quarantined
  immediately).  Quarantine is a kill the router itself decides —
  the same migration path drains the replica's work.  With a
  ``watchdog=``, every pump beats the heartbeat file first, carrying
  the replica's name — so a wedged pump leaves the stalled replica
  NAMED on disk for ``tools/tpu_watch.py``.
- **deadlines**: an SLO class (or a per-request override) may carry
  ``deadline_s``.  Unmeetable deadlines are rejected at admission
  (``deadline_unmeetable`` — the budget-headroom discipline); a
  missed deadline cancels the request wherever it runs and either
  re-routes it (up to ``max_retries``, deadline re-armed) or
  completes it with the terminal reason ``"deadline"`` — its partial
  stream is a committed PREFIX of the reference stream, never
  garbage.
- **hedging**: after ``hedge_after_s`` a still-running request
  spawns ONE duplicate on a different replica — safe because both
  copies produce the SAME stream (seeded/greedy determinism), so
  first-commit-wins is exact: the winner's completion is recorded,
  the loser is cancelled, token identity is preserved by
  construction.
- **brownout**: under page pressure or queue growth the router walks
  :class:`BrownoutPolicy`'s ladder — speculation off, then prefill
  chunks throttled, then the lowest-priority class shed at admission
  (``"brownout"`` rejections) — and walks back down with hysteresis.
  Every transition is an emitted ``brownout`` event.

A ``journal=`` (:class:`~apex_tpu.fleet.journal.RequestJournal`)
makes the log durable: admissions are journaled write-ahead and every
step's harvested deltas land in one batched append, so a SIGKILLed
process recovers with :func:`~apex_tpu.fleet.journal.recover_journal`
+ :meth:`FleetRouter.resume_from_journal` — completed requests keep
their recorded streams, in-flight ones re-admit token-identically.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from apex_tpu.fleet.failover import RequestLog, resume_request
from apex_tpu.serving.kv_cache import prompt_page_hashes
from apex_tpu.serving.serve import ContinuousBatcher, Request

__all__ = ["SLOClass", "FleetPolicy", "BrownoutPolicy", "Replica",
           "FleetCompletion", "FleetRouter", "INTERACTIVE", "BATCH"]

_ROUTINGS = ("affinity", "least_loaded", "round_robin")

#: replica roles: ``prefill`` ingests prompts and hands decode-ready
#: streams off by page movement, ``decode`` receives streams only by
#: handoff, ``unified`` does both (the pre-disaggregation behavior)
_REPLICA_ROLES = ("prefill", "decode", "unified")


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One latency class.  ``priority`` orders admission (lower admits
    first); ``max_queue`` caps the class's fleet-wide QUEUED requests —
    beyond it, :meth:`FleetRouter.submit` rejects (admission control:
    an interactive class would rather shed than queue past its SLO,
    a batch class usually leaves it ``None``/unbounded).

    ``deadline_s`` arms a per-request deadline at admission (see the
    module docstring's deadline semantics); ``max_retries`` bounds how
    many times a deadline miss re-routes before the terminal
    ``"deadline"`` completion; ``hedge_after_s`` spawns one duplicate
    on another replica after that much arrival-anchored wall time —
    all None/0 by default (no timed behavior)."""

    name: str
    priority: int = 0
    max_queue: Optional[int] = None
    deadline_s: Optional[float] = None
    max_retries: int = 0
    hedge_after_s: Optional[float] = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("SLO class needs a name")
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None)")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0 (or None)")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.hedge_after_s is not None and self.hedge_after_s <= 0:
            raise ValueError("hedge_after_s must be > 0 (or None)")


INTERACTIVE = SLOClass("interactive", priority=0)
BATCH = SLOClass("batch", priority=1)


@dataclasses.dataclass(frozen=True)
class BrownoutPolicy:
    """The degradation ladder: three rungs, each an explicit trade of
    quality-of-service for headroom, shed in policy order —

    1. speculation off (drafting burns pages and verify FLOPs for
       latency; pressure wants the pages back),
    2. prefill chunks throttled to every ``chunk_throttle``-th window
       iteration (admissions ingest slower, decode keeps its budget),
    3. the LOWEST-priority SLO class rejected at admission
       (``"brownout"`` — batch sheds before interactive degrades).

    A rung engages when the fleet's minimum free-page fraction drops
    to ``page_frac[i]`` or its queued depth reaches
    ``queue_depth[i]``; it releases one rung per step only when the
    triggers clear by ``recover_margin`` (hysteresis — a fleet
    hovering at a threshold must not flap).  Declarative and frozen,
    like :class:`FleetPolicy` itself: every transition the router
    makes is readable off this object, and emitted as a ``brownout``
    event."""

    page_frac: Tuple[float, float, float] = (0.25, 0.12, 0.05)
    queue_depth: Tuple[int, int, int] = (8, 16, 32)
    chunk_throttle: int = 2
    recover_margin: float = 1.5

    def __post_init__(self):
        if len(self.page_frac) != 3 or len(self.queue_depth) != 3:
            raise ValueError(
                "the ladder has exactly 3 rungs: page_frac and "
                "queue_depth must each have 3 thresholds")
        if not all(0.0 <= f < 1.0 for f in self.page_frac):
            raise ValueError(
                f"page_frac thresholds must be in [0, 1): "
                f"{self.page_frac}")
        if list(self.page_frac) != sorted(self.page_frac,
                                          reverse=True):
            raise ValueError(
                f"page_frac must be non-increasing (rung i+1 is MORE "
                f"pressure): {self.page_frac}")
        if any(d < 1 for d in self.queue_depth):
            raise ValueError(
                f"queue_depth thresholds must be >= 1: "
                f"{self.queue_depth}")
        if list(self.queue_depth) != sorted(self.queue_depth):
            raise ValueError(
                f"queue_depth must be non-decreasing: "
                f"{self.queue_depth}")
        if self.chunk_throttle < 2:
            raise ValueError(
                "chunk_throttle must be >= 2 (1 would make rung 2 a "
                "no-op)")
        if self.recover_margin <= 1.0:
            raise ValueError(
                "recover_margin must be > 1 (hysteresis needs a gap)")


@dataclasses.dataclass(frozen=True)
class FleetPolicy:
    """The fleet's ONE declarative policy: SLO classes, routing mode,
    load-score weights.  Every router decision reads off this object.

    ``routing``: ``"affinity"`` (prefix-match first, least-loaded
    tie-break/fallback), ``"least_loaded"`` (load only), or
    ``"round_robin"`` (the baseline: cycles replicas and ignores class
    priority).  The load score is
    ``w_queue * queue_depth + w_slots * live_slots
    - w_pages * free_page_fraction`` — smaller is less loaded."""

    classes: Tuple[SLOClass, ...] = (INTERACTIVE, BATCH)
    routing: str = "affinity"
    w_queue: float = 1.0
    w_slots: float = 1.0
    w_pages: float = 1.0
    #: static per-fleet-step time floor for the admission-time
    #: deadline feasibility check (0 disables it): a request needing
    #: ``min_steps`` serving steps with ``min_steps * step_floor_s``
    #: past its deadline is rejected as ``deadline_unmeetable``
    step_floor_s: float = 0.0
    #: a pump slower than this is a stalled replica — quarantined on
    #: the spot (None disables the stall check)
    pump_timeout_s: Optional[float] = None
    #: consecutive pump exceptions before a replica is quarantined
    #: (a successful pump resets the count — transient faults heal)
    max_replica_faults: int = 3
    #: the degradation ladder (None = no brownout behavior)
    brownout: Optional[BrownoutPolicy] = None
    #: per-replica roles by INDEX (``"prefill"`` / ``"decode"`` /
    #: ``"unified"``); None = all unified.  Any non-unified role makes
    #: the fleet DISAGGREGATED: prompts route to prefill-capable
    #: replicas only, and finished prefills hand their KV pages off to
    #: decode-capable replicas (:meth:`FleetRouter._handoff_sweep`) —
    #: prefill compute and decode weight-streaming stop stealing each
    #: other's step budget
    roles: Optional[Tuple[str, ...]] = None

    def __post_init__(self):
        if self.routing not in _ROUTINGS:
            raise ValueError(
                f"routing must be one of {_ROUTINGS}, "
                f"got {self.routing!r}")
        if self.roles is not None:
            bad = [x for x in self.roles if x not in _REPLICA_ROLES]
            if bad:
                raise ValueError(
                    f"unknown replica roles {bad} — roles must be "
                    f"among {_REPLICA_ROLES}")
            if "prefill" in self.roles and not any(
                    x in ("decode", "unified") for x in self.roles):
                raise ValueError(
                    "prefill-role replicas hand every stream off — "
                    "the fleet needs at least one decode-capable "
                    "(decode or unified) replica")
            if "decode" in self.roles and not any(
                    x in ("prefill", "unified") for x in self.roles):
                raise ValueError(
                    "pure-decode replicas receive work only by page "
                    "handoff — the fleet needs at least one "
                    "prefill-capable replica")
        if not self.classes:
            raise ValueError("policy needs at least one SLO class")
        names = [c.name for c in self.classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO class names: {names}")
        if self.step_floor_s < 0:
            raise ValueError("step_floor_s must be >= 0")
        if self.pump_timeout_s is not None and self.pump_timeout_s <= 0:
            raise ValueError("pump_timeout_s must be > 0 (or None)")
        if self.max_replica_faults < 1:
            raise ValueError("max_replica_faults must be >= 1")

    def cls(self, name: str) -> SLOClass:
        for c in self.classes:
            if c.name == name:
                return c
        raise ValueError(
            f"unknown SLO class {name!r} "
            f"(policy has {[c.name for c in self.classes]})")


class Replica:
    """One fleet member: a named batcher plus its liveness and the
    fault-injection seam.  ``kill()`` marks it dead immediately;
    ``fail_after(n)`` arms a deterministic death after ``n`` harvest
    windows — the in-process analog of the resilience tier's
    ``tools/fault_drill.py`` SIGKILL, placed at the only boundary an
    in-process replica has (between windows; a real preemption
    additionally loses the unharvested window, which the replay
    contract already treats as uncommitted)."""

    def __init__(self, name: str, batcher: ContinuousBatcher,
                 role: str = "unified"):
        if role not in _REPLICA_ROLES:
            raise ValueError(
                f"unknown replica role {role!r} — must be among "
                f"{_REPLICA_ROLES}")
        self.name = str(name)
        self.batcher = batcher
        #: disaggregation role (``FleetPolicy.roles`` overrides it at
        #: router construction)
        self.role = role
        self.alive = True
        self.windows = 0
        self.fail_at: Optional[int] = None
        #: health-monitor state: total and consecutive pump faults,
        #: why the router quarantined it (None = not quarantined —
        #: a ``kill()`` is death, not quarantine), the last fault
        self.faults = 0
        self.consecutive_faults = 0
        self.quarantined: Optional[str] = None
        self.last_error: Optional[str] = None

    def kill(self) -> None:
        self.alive = False

    def fail_after(self, windows: int) -> None:
        if windows < 0:
            raise ValueError("fail_after expects >= 0 windows")
        self.fail_at = int(windows)


@dataclasses.dataclass
class FleetCompletion:
    """A completed fleet request: the FULL stitched token stream (every
    migration's committed tokens plus the final continuation), against
    the ORIGINAL prompt length.  ``ttft_s``/``duration_s`` are
    arrival-anchored (queue wait included — what an SLO sees), accurate
    to the harvest boundary."""

    uid: Any
    tokens: List[int]
    prompt_len: int
    reason: str
    slo: str
    replica: str
    replays: int = 0
    ttft_s: Optional[float] = None
    duration_s: Optional[float] = None
    #: True when a hedged duplicate won the race (the stream is still
    #: token-identical — determinism is why hedging is safe at all)
    hedged: bool = False
    #: page-level ownership transfers the request rode (disaggregated
    #: prefill→decode moves — no recompute, unlike ``replays``)
    handoffs: int = 0

    @property
    def itl_ms(self) -> Optional[float]:
        """Mean inter-token latency (ms) over the request's own stream
        — first token to completion, arrival-clock, harvest-granular."""
        if self.ttft_s is None or self.duration_s is None or \
                len(self.tokens) < 2:
            return None
        return ((self.duration_s - self.ttft_s)
                / (len(self.tokens) - 1) * 1e3)


class FleetRouter:
    """Route requests over replicas per a :class:`FleetPolicy`.

    ``replicas`` are :class:`Replica` objects or bare batchers (wrapped
    as ``r0``, ``r1``, ...).  All replicas must share one cache config
    family — same ``page_size`` (the routing key's unit) and prompt
    window.  ``logger`` is an optional
    :class:`~apex_tpu.telemetry.MetricsLogger`; the router adds
    ``request_routed`` / ``request_rejected`` / ``request_migrated`` /
    ``replica_dead`` events on top of each batcher's own stream, and
    the fault-tolerance tier adds ``replica_fault`` /
    ``replica_quarantined`` / ``deadline_miss`` / ``hedge_spawn`` /
    ``hedge_win`` / ``hedge_loss`` / ``brownout`` /
    ``journal_replayed``.

    ``journal`` is an optional
    :class:`~apex_tpu.fleet.journal.RequestJournal` — admissions are
    journaled write-ahead inside :meth:`submit` and every
    :meth:`step` ends with one batched delta sync; ``watchdog`` is an
    optional :class:`~apex_tpu.resilience.watchdog.Watchdog` beaten
    before every pump with the replica's serving fields, so a wedged
    pump leaves the stalled replica named in the heartbeat file.

    Drive it with :meth:`submit` + :meth:`step` (one harvest window on
    every live replica per step — no replica blocks another), or
    :meth:`drain` to run pending work to completion.  Results land in
    ``self.completions`` (uid -> :class:`FleetCompletion`)."""

    def __init__(
        self,
        replicas: Sequence[Any],
        policy: Optional[FleetPolicy] = None,
        *,
        logger: Optional[Any] = None,
        clock=time.perf_counter,
        journal: Optional[Any] = None,
        watchdog: Optional[Any] = None,
    ):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas: List[Replica] = [
            r if isinstance(r, Replica) else Replica(f"r{i}", r)
            for i, r in enumerate(replicas)
        ]
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        sizes = {r.batcher.cache.config.page_size
                 for r in self.replicas}
        if len(sizes) != 1:
            raise ValueError(
                f"replicas disagree on page_size {sorted(sizes)} — "
                "the routing key is per-page, all replicas must share "
                "one cache config family")
        self.policy = policy if policy is not None else FleetPolicy()
        if self.policy.roles is not None:
            if len(self.policy.roles) != len(self.replicas):
                raise ValueError(
                    f"policy.roles names {len(self.policy.roles)} "
                    f"replicas but the fleet has "
                    f"{len(self.replicas)}")
            for r, role in zip(self.replicas, self.policy.roles):
                r.role = role
        #: any non-unified role => disaggregated scheduling: role-aware
        #: routing plus the per-step handoff sweep
        self._disagg = any(r.role != "unified" for r in self.replicas)
        if self._disagg:
            fams = {r.batcher.cache.compat_key()
                    for r in self.replicas}
            if len(fams) != 1:
                raise ValueError(
                    "disaggregated fleets move KV pages between "
                    "replicas — every cache must share one page "
                    f"layout (compat_key), got {len(fams)} distinct")
            for r in self.replicas:
                if r.role == "prefill":
                    r.batcher.decode_enabled = False
        #: staged handoff packets awaiting destination capacity:
        #: {"uid", "src", "dst", "packet", "export_s", "replays",
        #: "handoffs"} — charged to the DESTINATION's load score only
        self._handoffs: List[dict] = []
        self.logger = logger
        self._clock = clock
        self.journal = journal
        self.watchdog = watchdog
        self._page_size = sizes.pop()
        self._max_prompt_len = min(
            r.batcher.max_prompt_len for r in self.replicas)
        self.log = RequestLog()
        self.completions: Dict[Any, FleetCompletion] = {}
        self.rejected: Dict[Any, str] = {}          # uid -> reason
        self._queues: Dict[str, collections.deque] = {
            r.name: collections.deque() for r in self.replicas}
        self._cls: Dict[Any, str] = {}              # uid -> class name
        self._by_name: Dict[str, Replica] = {
            r.name: r for r in self.replicas}
        self._rr = 0
        self._steps = 0
        #: live hedges: uid -> {"replica", "base" (stream at spawn)}
        self._hedges: Dict[Any, dict] = {}
        self._hedged_once: set = set()   # one hedge per request, ever
        self.brownout_level = 0
        #: skip the per-step deadline sweep until any deadline exists
        self._deadlines_live = any(
            c.deadline_s is not None for c in self.policy.classes)
        self._has_hedging = any(
            c.hedge_after_s is not None for c in self.policy.classes)
        self.stats = {
            "submitted": 0, "rejected": 0, "migrations": 0,
            "affinity_routed": 0,
            "replica_faults": 0, "quarantined": 0,
            "deadline_misses": 0, "deadline_retries": 0,
            "hedges": 0, "hedge_wins": 0, "hedge_losses": 0,
            "brownout_transitions": 0, "resumed_from_journal": 0,
            "handoffs": 0, "handoff_pages": 0, "handoff_bytes": 0,
            "routed": {r.name: 0 for r in self.replicas},
        }

    # ------------------------------------------------------------ events
    def _event(self, kind: str, **fields) -> None:
        if self.logger is not None:
            self.logger.event(kind, **fields)

    # ------------------------------------------------------------- state
    @property
    def pending(self) -> int:
        """Requests submitted but not yet completed."""
        return self.log.pending()

    def queue_depth(self, cls_name: Optional[str] = None) -> int:
        """Fleet-wide QUEUED (not yet admitted) requests, optionally
        restricted to one SLO class."""
        n = 0
        for q in self._queues.values():
            for req in q:
                if cls_name is None or self._cls[req.uid] == cls_name:
                    n += 1
        return n

    def _inbound(self, name: str) -> int:
        """Staged handoff packets bound for the named replica — load
        it has accepted ownership of but not yet imported."""
        return sum(1 for p in self._handoffs if p["dst"] == name)

    def _load(self, r: Replica) -> float:
        """Host-mirror load score — the telemetry-gauge quantities,
        read directly (no device sync, no jsonl round-trip).  A
        mid-handoff request counts against its DESTINATION only (the
        ``_inbound`` term): the source released its slot at export, so
        without the term the request would vanish from every score
        while staged — and with the old holder-based accounting it was
        counted on BOTH sides until the import landed."""
        p = self.policy
        cfg = r.batcher.cache.config
        free_frac = (r.batcher.cache.allocator.num_free
                     / max(1, cfg.num_pages - 1))
        return (p.w_queue * len(self._queues[r.name])
                + p.w_slots * (r.batcher.live_slots
                               + self._inbound(r.name))
                - p.w_pages * free_frac)

    # ------------------------------------------------------------- route
    def _route(self, request: Request) -> Tuple[Replica, int]:
        """Pick the serving replica; returns ``(replica,
        affinity_tokens)``.  Deterministic: ties break on replica
        order."""
        alive = [r for r in self.replicas if r.alive]
        if not alive:
            raise RuntimeError("no replica is alive")
        # disaggregation: prompts go to prefill-capable replicas; a
        # pure-decode replica receives work by page handoff, never by
        # routing — unless nothing prefill-capable is left alive
        cands = [r for r in alive if r.role != "decode"] or alive
        if self.policy.routing == "round_robin":
            r = cands[self._rr % len(cands)]
            self._rr += 1
            return r, 0
        key = (prompt_page_hashes(request.prompt, self._page_size)
               if self.policy.routing == "affinity" else [])
        best, best_score, best_aff = None, None, 0
        for i, r in enumerate(cands):
            aff = r.batcher.cache.match_len(key) if key else 0
            # chunk budget: in a disaggregated fleet, prompts steer by
            # the chunks a prefill replica still owes, not just queue
            # length — the prefill-pressure half of role-aware routing
            pressure = (self.policy.w_queue
                        * r.batcher.pending_prefill_chunks
                        if self._disagg else 0.0)
            score = (-aff, self._load(r) + pressure, i)
            if best_score is None or score < best_score:
                best, best_score, best_aff = r, score, aff
        return best, best_aff

    # ------------------------------------------------------------ submit
    def _deadline_feasible(self, deadline_s: float, plen: int,
                           max_new: int) -> bool:
        """Static admission arithmetic, the budget-headroom
        discipline applied to time: the request needs at least one
        serving step per prefill chunk (one for a monolithic prefill)
        plus one decode step per generated token after the first —
        if that floor already overruns the deadline, reject now
        instead of cancelling later."""
        if deadline_s <= 0:
            return False
        floor = self.policy.step_floor_s
        if floor <= 0:
            return True
        chunk = self.replicas[0].batcher.prefill_chunk
        chunks = -(-plen // chunk) if chunk else 1
        min_steps = chunks + max_new - 1
        return min_steps * floor <= deadline_s

    def submit(self, request: Request, slo: Optional[str] = None,
               *, t_arrive: Optional[float] = None,
               deadline_s: Optional[float] = None) -> bool:
        """Admission-control one request into the fleet.  Returns False
        (and emits ``request_rejected``) when the request can never be
        served (prompt + replay headroom past the prompt window, or
        more pages than any replica's pool), its class queue is full,
        its deadline is already unmeetable, or the brownout ladder is
        shedding its class; True once it is routed and logged.
        ``slo`` defaults to the policy's first (highest-priority)
        class; ``deadline_s`` overrides the class's own (relative to
        arrival).

        The prompt-window check reserves REPLAY headroom: migration
        re-admits ``prompt + emitted`` as a prompt, so
        ``len(prompt) + max_new_tokens - 1`` must fit
        ``max_prompt_len`` — enforced here, not discovered at failover
        time."""
        cls = self.policy.cls(slo) if slo is not None \
            else self.policy.classes[0]
        cfg = self.replicas[0].batcher.cache.config
        plen = len(request.prompt)
        total = plen + request.max_new_tokens
        dl = deadline_s if deadline_s is not None else cls.deadline_s
        reason = None
        if plen + request.max_new_tokens - 1 > self._max_prompt_len:
            reason = "too_large"
        elif (total > cfg.max_len
                or cfg.tokens_to_pages(total) > cfg.num_pages - 1):
            reason = "too_large"
        elif dl is not None and not self._deadline_feasible(
                float(dl), plen, request.max_new_tokens):
            reason = "deadline_unmeetable"
        elif cls.max_queue is not None and \
                self.queue_depth(cls.name) >= cls.max_queue:
            reason = "queue_full"
        elif (self.brownout_level >= 3
                and len(self.policy.classes) > 1
                and cls.priority == max(
                    c.priority for c in self.policy.classes)):
            reason = "brownout"
        if reason is not None:
            self.rejected[request.uid] = reason
            self.stats["rejected"] += 1
            self._event("request_rejected", uid=request.uid,
                        slo=cls.name, reason=reason)
            return False
        replica, aff = self._route(request)
        now = self._clock() if t_arrive is None else float(t_arrive)
        e = self.log.admit(request, cls.name, replica.name, now)
        if dl is not None:
            e.deadline_rel = float(dl)
            e.deadline = now + float(dl)
            self._deadlines_live = True
        self._cls[request.uid] = cls.name
        if self.journal is not None:
            self.journal.admit(e)       # write-ahead: durable first
        self._queues[replica.name].append(request)
        self.stats["submitted"] += 1
        self.stats["routed"][replica.name] += 1
        if aff > 0:
            self.stats["affinity_routed"] += 1
        self._event("request_routed", uid=request.uid,
                    replica=replica.name, slo=cls.name, affinity=aff)
        return True

    # -------------------------------------------------------------- step
    def _pump_order(self, name: str) -> collections.deque:
        """The replica's admission queue for this pump: class priority
        first (stable — FIFO within a class), unless the round-robin
        baseline, which is FIFO across classes too."""
        items = list(self._queues[name])
        if self.policy.routing != "round_robin":
            prio = {c.name: c.priority for c in self.policy.classes}
            items.sort(key=lambda req: prio[self._cls[req.uid]])
        return collections.deque(items)

    def step(self) -> bool:
        """One fleet scheduling turn: fire any armed fault seams,
        migrate work off dead (killed or quarantined) replicas,
        re-evaluate the brownout ladder, pump every live replica one
        harvest window (heartbeat first, health-checked after),
        absorb progress and completions into the log, sweep deadlines
        and hedges, and sync the durable journal.  Returns True while
        requests remain pending."""
        self._steps += 1
        for r in self.replicas:
            if r.alive and r.fail_at is not None \
                    and r.windows >= r.fail_at:
                r.kill()
        for r in self.replicas:
            if not r.alive:
                self._drop_hedges_on(r.name, "replica_dead")
                if self._queues[r.name] or self.log.inflight_on(r.name):
                    self._migrate(r)
        self._brownout_eval()
        for r in self.replicas:
            if not r.alive:
                continue
            work = self._pump_order(r.name)
            if not work and r.batcher.live_slots == 0:
                continue
            self._beat(r)
            t0 = self._clock()
            try:
                r.batcher.pump(work)
            except Exception as err:        # noqa: BLE001 — a faulting
                # replica must not take the fleet down; quarantine
                # after max_replica_faults and migrate its work
                self._queues[r.name] = work
                self._replica_fault(r, err)
                continue
            dur = self._clock() - t0
            r.consecutive_faults = 0
            r.windows += 1
            self._queues[r.name] = work
            self._absorb(r)
            if self.policy.pump_timeout_s is not None \
                    and dur > self.policy.pump_timeout_s:
                self._quarantine(r, "stall")
        self._handoff_sweep()
        self._enforce_deadlines()
        self._spawn_hedges()
        if self.journal is not None:
            self.journal.sync(self.log)
        return self.pending > 0

    # ------------------------------------------------------------ health
    def _beat(self, r: Replica) -> None:
        """Heartbeat BEFORE the pump, carrying the replica's serving
        fields — if the pump then wedges, the heartbeat file names
        the stalled replica (``tools/tpu_watch.py`` reads it)."""
        if self.watchdog is None:
            return
        self.watchdog.beat(step=self._steps, extra={
            "replica": r.name,
            "serving_step": int(r.batcher.steps),
            "live_slots": int(r.batcher.live_slots),
        })

    def _replica_fault(self, r: Replica, err: BaseException) -> None:
        r.faults += 1
        r.consecutive_faults += 1
        r.last_error = repr(err)
        self.stats["replica_faults"] += 1
        self._event("replica_fault", replica=r.name, error=repr(err),
                    consecutive=r.consecutive_faults)
        if r.consecutive_faults >= self.policy.max_replica_faults:
            self._quarantine(r, "faults")

    def _quarantine(self, r: Replica, cause: str) -> None:
        """A quarantine is a kill the router decides itself: the
        replica is marked dead and the NEXT step's migration pass
        drains its queue and in-flight slots exactly like
        ``Replica.kill()`` — pending work keeps :meth:`drain`
        stepping, so nothing strands."""
        if not r.alive:
            return
        r.alive = False
        r.quarantined = cause
        self.stats["quarantined"] += 1
        self._event("replica_quarantined", replica=r.name,
                    cause=cause, faults=r.faults, windows=r.windows,
                    error=r.last_error)

    def drain(self, max_steps: int = 100_000
              ) -> Dict[Any, FleetCompletion]:
        """Step until nothing is pending (bounded by ``max_steps`` so a
        scheduling bug hangs a test, not a host)."""
        steps = 0
        while self.step():
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain in {max_steps} steps "
                    f"({self.pending} requests still pending)")
        return self.completions

    # ----------------------------------------------------------- absorb
    def _absorb(self, r: Replica) -> None:
        now = self._clock()
        # hedge progress is invisible here by design: record_progress
        # skips entries whose holder is a different replica, so only
        # the primary's stream feeds the log until a commit decides
        self.log.record_progress(r.name, r.batcher.progress(), now)
        for uid, comp in r.batcher.completions.items():
            if uid in self.completions or uid not in self.log:
                continue
            e = self.log.get(uid)
            h = self._hedges.get(uid)
            if h is not None and h["replica"] == r.name:
                # the HEDGED duplicate finished on this replica
                self._hedges.pop(uid)
                if e.done:
                    # the primary reached a terminal state first
                    self.stats["hedge_losses"] += 1
                    self._event("hedge_loss", uid=uid, replica=r.name,
                                cause="primary_won")
                    continue
                # first-commit-wins: cancel the primary, record the
                # hedge's completion.  The full stream is the spawn
                # base plus the hedge's tokens — token-identical to
                # what the primary would have produced (determinism
                # is the safety argument), so stitching past the
                # primary's extra progress is exact.
                full = list(h["base"]) + list(comp.tokens)
                delta = full[len(e.replayed):]
                pq = self._queues.get(e.replica)
                if pq:
                    self._queues[e.replica] = collections.deque(
                        x for x in pq if x.uid != uid)
                prim = self._by_name.get(e.replica)
                if prim is not None and prim.alive:
                    prim.batcher.cancel(uid)
                e.replica = r.name
                e = self.log.complete(uid, delta, comp.reason, now)
                self.completions[uid] = FleetCompletion(
                    uid=uid, tokens=list(e.emitted),
                    prompt_len=len(e.request.prompt),
                    reason=e.reason, slo=e.slo, replica=r.name,
                    replays=e.replays, hedged=True,
                    handoffs=e.handoffs,
                    ttft_s=(None if e.t_first is None
                            else e.t_first - e.t_arrive),
                    duration_s=now - e.t_arrive,
                )
                self.stats["hedge_wins"] += 1
                self._event("hedge_win", uid=uid, replica=r.name,
                            tokens=len(e.emitted))
                continue
            if e.done or e.replica != r.name:
                continue
            e = self.log.complete(uid, comp.tokens, comp.reason, now)
            self.completions[uid] = FleetCompletion(
                uid=uid, tokens=list(e.emitted),
                prompt_len=len(e.request.prompt),
                reason=e.reason, slo=e.slo, replica=r.name,
                replays=e.replays, handoffs=e.handoffs,
                ttft_s=(None if e.t_first is None
                        else e.t_first - e.t_arrive),
                duration_s=now - e.t_arrive,
            )
            if uid in self._hedges:
                self._drop_hedge(uid, "primary_won")

    # ----------------------------------------------------------- handoff
    def _decode_target(self) -> Optional[Replica]:
        """The least-loaded decode-capable replica (pure decode
        preferred over unified — that is what the role exists for);
        None when nothing decode-capable is alive."""
        best, best_score = None, None
        for i, r in enumerate(self.replicas):
            if not r.alive or r.role == "prefill":
                continue
            score = (0 if r.role == "decode" else 1, self._load(r), i)
            if best_score is None or score < best_score:
                best, best_score = r, score
        return best

    def _handoff_sweep(self) -> None:
        """The disaggregation engine, once per fleet step AFTER every
        pump+absorb (so the log's ``emitted`` and the packet's tokens
        agree): export decode-ready streams off prefill replicas as
        staged :class:`~apex_tpu.serving.serve.HandoffPacket`\\ s —
        each a journaled ownership transfer — then land staged packets
        on their destination as capacity allows (same step when the
        destination has a free slot).  The contract end to end:

        - **durability first**: the journal's ``handoff`` record is
          written BEFORE any pages move, and the packet's tokens are
          already journaled progress — a crash at any point recovers
          the stream token-identically (at worst via recompute).
        - **no double-count**: the source slot is released at export;
          the staged packet charges the destination's load score via
          ``_inbound`` until imported.
        - **staleness**: a packet whose log entry completed, changed
          holder (deadline retry, dead-destination migration) or
          advanced its replay/handoff counters is dropped — the
          recompute path owns the request; page content is always
          regenerable.
        - **fallback**: with every decode-capable replica dead, the
          prefill replicas flip ``decode_enabled`` back on (one-way,
          ``role_fallback`` event) so streams still finish."""
        if not self._disagg:
            return
        if not any(r.alive and r.role != "prefill"
                   for r in self.replicas):
            for r in self.replicas:
                if r.alive and not r.batcher.decode_enabled:
                    r.batcher.decode_enabled = True
                    self._event("role_fallback", replica=r.name)
            return
        # ---- export: prefill replicas shed decode-ready streams
        for r in self.replicas:
            if not r.alive or r.role != "prefill" \
                    or r.batcher.decode_enabled:
                continue    # decode_enabled: a past fallback flipped it
            for uid in r.batcher.handoff_ready():
                if uid not in self.log:
                    continue
                e = self.log.get(uid)
                if e.done or e.replica != r.name:
                    continue    # a hedge duplicate — never exported
                dst = self._decode_target()
                if dst is None:
                    return
                if (self._inbound(dst.name)
                        >= dst.batcher.cache.config.max_seqs):
                    continue    # staging bounded by destination slots
                if self.journal is not None:
                    self.journal.handoff(uid, r.name, dst.name)
                t0 = self._clock()
                packet = r.batcher.export_request(uid)
                if packet is None:
                    continue
                self.log.handoff(uid, dst.name)
                self._handoffs.append({
                    "uid": uid, "src": r.name, "dst": dst.name,
                    "packet": packet,
                    "export_s": self._clock() - t0,
                    "replays": e.replays, "handoffs": e.handoffs,
                })
        # ---- import: land staged packets where capacity allows
        for pk in list(self._handoffs):
            uid = pk["uid"]
            e = self.log.get(uid) if uid in self.log else None
            if e is None or e.done or e.replica != pk["dst"] \
                    or e.replays != pk["replays"] \
                    or e.handoffs != pk["handoffs"]:
                # completed / cancelled / re-routed since staging: the
                # packet is stale, the recompute path owns the request
                self._handoffs.remove(pk)
                continue
            dst = self._by_name.get(pk["dst"])
            if dst is None or not dst.alive:
                continue    # the migration pass re-routes next step
            t0 = self._clock()
            if not dst.batcher.import_request(pk["packet"]):
                continue                # backpressure: stay staged
            self._handoffs.remove(pk)
            self.stats["handoffs"] += 1
            self.stats["handoff_pages"] += pk["packet"].n_pages
            self.stats["handoff_bytes"] += pk["packet"].wire_bytes
            self._event(
                "kv_handoff", uid=uid, src=pk["src"], dst=pk["dst"],
                pages=pk["packet"].n_pages,
                bytes=pk["packet"].wire_bytes,
                tokens=len(pk["packet"].tokens),
                dur_s=round(pk["export_s"]
                            + (self._clock() - t0), 6))

    # --------------------------------------------------------- deadlines
    def _cancel_everywhere(self, e) -> Optional[List[int]]:
        """Remove a request from its holder (queue entry, in-flight
        slot, and any live hedge); returns the holder's harvested
        delta (relative to ``e.replayed``), or None if it was only
        queued."""
        uid = e.request.uid
        q = self._queues.get(e.replica)
        if q is not None and any(x.uid == uid for x in q):
            self._queues[e.replica] = collections.deque(
                x for x in q if x.uid != uid)
        rep = self._by_name.get(e.replica)
        toks = (rep.batcher.cancel(uid)
                if rep is not None and rep.alive else None)
        self._drop_hedge(uid, "cancelled")
        return toks

    def _enforce_deadlines(self) -> None:
        """The per-step deadline sweep: a missed deadline cancels the
        request wherever it runs, then either re-routes it with a
        re-armed deadline (``max_retries`` budget, replay semantics
        identical to migration — the partial stream rides along) or
        completes it with the terminal reason ``"deadline"``.  Either
        way the request's stream stays a committed prefix of the
        deterministic reference — never truncated mid-commit, never
        corrupted."""
        if not self._deadlines_live:
            return
        now = self._clock()
        for e in self.log.entries():
            if e.done or e.deadline is None or now < e.deadline:
                continue
            uid = e.request.uid
            cls = self.policy.cls(e.slo)
            self.stats["deadline_misses"] += 1
            budget_left = e.request.max_new_tokens - len(e.emitted)
            retry = (e.deadline_retries < cls.max_retries
                     and budget_left >= 1
                     and any(r.alive for r in self.replicas))
            toks = self._cancel_everywhere(e)
            self._event("deadline_miss", uid=uid, slo=e.slo,
                        emitted=len(e.emitted), retry=retry,
                        replays=e.replays)
            if retry:
                e.deadline_retries += 1
                self.stats["deadline_retries"] += 1
                req = resume_request(e)
                target, aff = self._route(req)
                self.log.reassign(uid, target.name)
                self._queues[target.name].append(req)
                self.stats["routed"][target.name] += 1
                e.deadline = now + (e.deadline_rel
                                    if e.deadline_rel is not None
                                    else cls.deadline_s)
                self._event("request_migrated", uid=uid,
                            replica=target.name, replays=e.replays,
                            affinity=aff, cause="deadline")
            else:
                e = self.log.complete(uid, toks or [], "deadline", now)
                self.completions[uid] = FleetCompletion(
                    uid=uid, tokens=list(e.emitted),
                    prompt_len=len(e.request.prompt),
                    reason="deadline", slo=e.slo, replica=e.replica,
                    replays=e.replays, handoffs=e.handoffs,
                    ttft_s=(None if e.t_first is None
                            else e.t_first - e.t_arrive),
                    duration_s=now - e.t_arrive,
                )

    # ----------------------------------------------------------- hedging
    def _spawn_hedges(self) -> None:
        """Arm one duplicate per eligible slow request: the hedge is
        a :func:`resume_request` re-admission (same uid, committed
        stream as prompt suffix) queued on the least-loaded OTHER
        replica.  Safe because both copies draw the SAME stream
        (seeded/greedy determinism + absolute-position key folds);
        :meth:`_absorb` resolves the race first-commit-wins."""
        if not self._has_hedging:
            return
        alive = [r for r in self.replicas if r.alive]
        if len(alive) < 2:
            return
        now = self._clock()
        for e in self.log.entries():
            uid = e.request.uid
            if e.done or uid in self._hedges \
                    or uid in self._hedged_once:
                continue
            cls = self.policy.cls(e.slo)
            if cls.hedge_after_s is None \
                    or now - e.t_arrive < cls.hedge_after_s:
                continue
            # never hedge onto a prefill-role replica: it would ingest
            # the replay and then wait for a handoff the sweep refuses
            # (hedge copies are not log holders) — a slot burned for
            # nothing
            cands = [r for r in alive if r.name != e.replica
                     and r.role != "prefill"]
            if not cands:
                continue
            try:
                req = resume_request(e)
            except ValueError:
                continue                    # no budget left: let the
            target = min(cands, key=self._load)  # completion land
            self._hedged_once.add(uid)
            self._hedges[uid] = {"replica": target.name,
                                 "base": list(e.emitted)}
            self._queues[target.name].append(req)
            self.stats["hedges"] += 1
            self._event("hedge_spawn", uid=uid, replica=target.name,
                        primary=e.replica, base=len(e.emitted))

    def _drop_hedge(self, uid: Any, cause: str) -> None:
        """Cancel a live hedge (queue entry and/or in-flight slot on
        the hedge replica); its harvested tokens are duplicates of a
        committed-or-regenerable prefix, so dropping them loses
        nothing."""
        h = self._hedges.pop(uid, None)
        if h is None:
            return
        q = self._queues.get(h["replica"])
        if q is not None and any(x.uid == uid for x in q):
            self._queues[h["replica"]] = collections.deque(
                x for x in q if x.uid != uid)
        rep = self._by_name.get(h["replica"])
        if rep is not None and rep.alive:
            rep.batcher.cancel(uid)
        self.stats["hedge_losses"] += 1
        self._event("hedge_loss", uid=uid, replica=h["replica"],
                    cause=cause)

    def _drop_hedges_on(self, name: str, cause: str) -> None:
        """A dead replica's hedges just evaporate — the primaries are
        unaffected (hedges never feed the log until they win)."""
        for uid in [u for u, h in self._hedges.items()
                    if h["replica"] == name]:
            self._drop_hedge(uid, cause)

    # ---------------------------------------------------------- brownout
    def _brownout_eval(self) -> None:
        """Walk the ladder: escalate immediately on any rung's
        trigger, de-escalate one rung per step only when the current
        rung's trigger clears by the recover margin (hysteresis).
        Every transition is a ``brownout`` event and re-applies the
        batcher levers (speculation flag, chunk throttle)."""
        bp = self.policy.brownout
        if bp is None:
            return
        alive = [r for r in self.replicas if r.alive]
        if not alive:
            return
        free = min(
            (r.batcher.cache.allocator.num_free
             / max(1, r.batcher.cache.config.num_pages - 1))
            for r in alive)
        qd = sum(len(q) for q in self._queues.values())
        target = 0
        for i in range(3):
            if free <= bp.page_frac[i] or qd >= bp.queue_depth[i]:
                target = i + 1
        lvl = self.brownout_level
        if target > lvl:
            new = target
        elif target < lvl:
            i = lvl - 1
            clear = (free >= min(1.0,
                                 bp.page_frac[i] * bp.recover_margin)
                     and qd <= bp.queue_depth[i] / bp.recover_margin)
            new = lvl - 1 if clear else lvl
        else:
            new = lvl
        if new == lvl:
            return
        self.brownout_level = new
        self.stats["brownout_transitions"] += 1
        self._event("brownout", from_level=lvl, to_level=new,
                    free_page_frac=round(free, 4), queue_depth=qd)
        for r in self.replicas:
            r.batcher.speculation_enabled = new < 1
            r.batcher.chunk_throttle = (bp.chunk_throttle
                                        if new >= 2 else 1)

    # ---------------------------------------------------------- failover
    def _migrate(self, dead: Replica) -> None:
        """Re-admit everything a dead replica held: queued requests
        move as-is, in-flight ones replay their committed tokens as
        prompt suffix (:func:`resume_request`).  Zero requests are
        lost; uncommitted (unharvested) tokens are regenerated, not
        recovered."""
        entries = self.log.inflight_on(dead.name)
        self._queues[dead.name].clear()
        self._event("replica_dead", replica=dead.name,
                    migrated=len(entries))
        for e in entries:
            # a live hedge is dropped BEFORE re-routing the primary:
            # otherwise the migration could land the primary on the
            # hedge's replica — two slots serving one uid
            self._drop_hedge(e.request.uid, "primary_migrated")
            req = resume_request(e)
            target, aff = self._route(req)
            self.log.reassign(req.uid, target.name)
            self._queues[target.name].append(req)
            self.stats["migrations"] += 1
            self.stats["routed"][target.name] += 1
            self._event("request_migrated", uid=req.uid,
                        replica=target.name, replays=e.replays,
                        affinity=aff)

    # ----------------------------------------------------------- journal
    def resume_from_journal(self, recovery) -> Dict[str, int]:
        """Rebuild fleet state from a
        :class:`~apex_tpu.fleet.journal.JournalRecovery` (a restarted
        process's first act, after the checkpoint seam rebuilt the
        weight pools): completed requests land straight in
        ``self.completions`` with their recorded streams; in-flight
        ones re-admit through the migration path — committed tokens
        replayed as prompt suffix, token-identical continuations.
        When the router carries a journal, its cursor is primed so
        only NEW tokens are journaled from here on (reuse ONE journal
        path across restarts).

        Returns ``{"resumed", "completed", "corrupt", "gapped"}``."""
        now = self._clock()
        resumed = completed = 0
        for uid, info in recovery.entries.items():
            if uid in self.log:
                continue
            try:
                slo = self.policy.cls(info["slo"]).name
            except ValueError:
                slo = self.policy.classes[0].name
            e = self.log.admit(info["request"], slo, "<journal>", now)
            e.emitted = list(info["emitted"])
            self._cls[uid] = slo
            if info["done"]:
                e.replayed = list(e.emitted)
                e.done, e.reason, e.t_done = True, info["reason"], now
                self.completions[uid] = FleetCompletion(
                    uid=uid, tokens=list(e.emitted),
                    prompt_len=len(info["request"].prompt),
                    reason=info["reason"], slo=slo,
                    replica="<journal>")
                completed += 1
                continue
            if len(e.emitted) >= info["request"].max_new_tokens:
                # the stream is complete but the terminal record was
                # lost with the process: close it out as budget
                e.replayed = list(e.emitted)
                e.done, e.reason, e.t_done = True, "budget", now
                self.completions[uid] = FleetCompletion(
                    uid=uid, tokens=list(e.emitted),
                    prompt_len=len(info["request"].prompt),
                    reason="budget", slo=slo, replica="<journal>")
                completed += 1
                continue
            if info.get("deadline_s") is not None:
                e.deadline_rel = float(info["deadline_s"])
                e.deadline = now + e.deadline_rel   # re-armed in full
                self._deadlines_live = True
            req = resume_request(e)
            target, aff = self._route(req)
            self.log.reassign(uid, target.name)
            self._queues[target.name].append(req)
            self.stats["routed"][target.name] += 1
            self.stats["resumed_from_journal"] += 1
            resumed += 1
            self._event("request_migrated", uid=uid,
                        replica=target.name, replays=e.replays,
                        affinity=aff, cause="journal")
        if self.journal is not None:
            self.journal.prime(self.log)
        out = {"resumed": resumed, "completed": completed,
               "corrupt": recovery.corrupt, "gapped": recovery.gapped}
        self._event("journal_replayed", **out)
        return out

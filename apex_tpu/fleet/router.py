"""Fleet-tier serving: one router over N continuous-batching replicas.

The serving stack below this module tops out at one
:class:`~apex_tpu.serving.serve.ContinuousBatcher` — one chip's worth
of users, no notion of a latency class, and a single point of failure.
This module is the scenario layer on top: N batcher replicas (the
SAME jitted ``decode_fns`` step functions drive every replica, each
over its own cache and pools, so the fleet adds ZERO compilations; a
replica may equally be a tp *group* wrapping a ``decode_fns(tp=)``
sharded build — the router never sees the mesh) behind one
:class:`FleetRouter` that decides, per request, WHO serves it and
WHEN.

Everything the router needs already exists as host-side mirrors — the
design rule is **no new host syncs**:

- **routing key**: the prefix cache's cumulative page hash
  (:func:`~apex_tpu.serving.kv_cache.prompt_page_hashes`) — replica-
  independent by construction, so the router hashes a prompt once and
  probes every replica's prefix index read-only
  (``PagedKVCache.match_len``).  Requests sharing a system prompt land
  on the replica whose pages already hold it; prefill chunks the match
  covers are never computed.
- **load score**: free KV pages (``allocator.num_free``), queue depth,
  live slots — the same quantities the batcher exports as the
  ``pages_free`` / ``pages_shared`` / ``live_slots`` / ``queue_depth``
  telemetry gauges.
- **SLO classes**: per-class queues drained in priority order at every
  pump (interactive ahead of batch on the SAME replica — stable sort,
  FIFO within a class) with per-class admission control: a class whose
  fleet-wide queue is at ``max_queue`` REJECTS instead of growing an
  unbounded backlog (``request_rejected`` event; the caller retries or
  sheds).

Policy is ONE declarative object (:class:`FleetPolicy`), not a pile of
flags — the veScale one-consistent-spec discipline: construct it once,
read any routing/admission decision off it.  ``routing="round_robin"``
is the deliberately dumb baseline (ignores affinity, load AND class
priority) the ``_dryrun_fleet`` gate and the bench rows compare
against.

Failover rides the request log (:mod:`apex_tpu.fleet.failover`):
killing a replica between windows — the in-process analog of the
resilience tier's SIGKILL drills, injected via ``Replica.kill()`` /
``Replica.fail_after(windows)`` — re-admits its queued AND in-flight
requests on surviving replicas with committed tokens replayed as
prompt suffix.  Zero requests are lost, and the replayed continuations
are token-identical (greedy or seeded) to an unkilled run.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from apex_tpu.fleet.failover import RequestLog, resume_request
from apex_tpu.serving.kv_cache import prompt_page_hashes
from apex_tpu.serving.serve import ContinuousBatcher, Request

__all__ = ["SLOClass", "FleetPolicy", "Replica", "FleetCompletion",
           "FleetRouter", "INTERACTIVE", "BATCH"]

_ROUTINGS = ("affinity", "least_loaded", "round_robin")


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One latency class.  ``priority`` orders admission (lower admits
    first); ``max_queue`` caps the class's fleet-wide QUEUED requests —
    beyond it, :meth:`FleetRouter.submit` rejects (admission control:
    an interactive class would rather shed than queue past its SLO,
    a batch class usually leaves it ``None``/unbounded)."""

    name: str
    priority: int = 0
    max_queue: Optional[int] = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("SLO class needs a name")
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None)")


INTERACTIVE = SLOClass("interactive", priority=0)
BATCH = SLOClass("batch", priority=1)


@dataclasses.dataclass(frozen=True)
class FleetPolicy:
    """The fleet's ONE declarative policy: SLO classes, routing mode,
    load-score weights.  Every router decision reads off this object.

    ``routing``: ``"affinity"`` (prefix-match first, least-loaded
    tie-break/fallback), ``"least_loaded"`` (load only), or
    ``"round_robin"`` (the baseline: cycles replicas and ignores class
    priority).  The load score is
    ``w_queue * queue_depth + w_slots * live_slots
    - w_pages * free_page_fraction`` — smaller is less loaded."""

    classes: Tuple[SLOClass, ...] = (INTERACTIVE, BATCH)
    routing: str = "affinity"
    w_queue: float = 1.0
    w_slots: float = 1.0
    w_pages: float = 1.0

    def __post_init__(self):
        if self.routing not in _ROUTINGS:
            raise ValueError(
                f"routing must be one of {_ROUTINGS}, "
                f"got {self.routing!r}")
        if not self.classes:
            raise ValueError("policy needs at least one SLO class")
        names = [c.name for c in self.classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO class names: {names}")

    def cls(self, name: str) -> SLOClass:
        for c in self.classes:
            if c.name == name:
                return c
        raise ValueError(
            f"unknown SLO class {name!r} "
            f"(policy has {[c.name for c in self.classes]})")


class Replica:
    """One fleet member: a named batcher plus its liveness and the
    fault-injection seam.  ``kill()`` marks it dead immediately;
    ``fail_after(n)`` arms a deterministic death after ``n`` harvest
    windows — the in-process analog of the resilience tier's
    ``tools/fault_drill.py`` SIGKILL, placed at the only boundary an
    in-process replica has (between windows; a real preemption
    additionally loses the unharvested window, which the replay
    contract already treats as uncommitted)."""

    def __init__(self, name: str, batcher: ContinuousBatcher):
        self.name = str(name)
        self.batcher = batcher
        self.alive = True
        self.windows = 0
        self.fail_at: Optional[int] = None

    def kill(self) -> None:
        self.alive = False

    def fail_after(self, windows: int) -> None:
        if windows < 0:
            raise ValueError("fail_after expects >= 0 windows")
        self.fail_at = int(windows)


@dataclasses.dataclass
class FleetCompletion:
    """A completed fleet request: the FULL stitched token stream (every
    migration's committed tokens plus the final continuation), against
    the ORIGINAL prompt length.  ``ttft_s``/``duration_s`` are
    arrival-anchored (queue wait included — what an SLO sees), accurate
    to the harvest boundary."""

    uid: Any
    tokens: List[int]
    prompt_len: int
    reason: str
    slo: str
    replica: str
    replays: int = 0
    ttft_s: Optional[float] = None
    duration_s: Optional[float] = None

    @property
    def itl_ms(self) -> Optional[float]:
        """Mean inter-token latency (ms) over the request's own stream
        — first token to completion, arrival-clock, harvest-granular."""
        if self.ttft_s is None or self.duration_s is None or \
                len(self.tokens) < 2:
            return None
        return ((self.duration_s - self.ttft_s)
                / (len(self.tokens) - 1) * 1e3)


class FleetRouter:
    """Route requests over replicas per a :class:`FleetPolicy`.

    ``replicas`` are :class:`Replica` objects or bare batchers (wrapped
    as ``r0``, ``r1``, ...).  All replicas must share one cache config
    family — same ``page_size`` (the routing key's unit) and prompt
    window.  ``logger`` is an optional
    :class:`~apex_tpu.telemetry.MetricsLogger`; the router adds
    ``request_routed`` / ``request_rejected`` / ``request_migrated`` /
    ``replica_dead`` events on top of each batcher's own stream.

    Drive it with :meth:`submit` + :meth:`step` (one harvest window on
    every live replica per step — no replica blocks another), or
    :meth:`drain` to run pending work to completion.  Results land in
    ``self.completions`` (uid -> :class:`FleetCompletion`)."""

    def __init__(
        self,
        replicas: Sequence[Any],
        policy: Optional[FleetPolicy] = None,
        *,
        logger: Optional[Any] = None,
        clock=time.perf_counter,
    ):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas: List[Replica] = [
            r if isinstance(r, Replica) else Replica(f"r{i}", r)
            for i, r in enumerate(replicas)
        ]
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        sizes = {r.batcher.cache.config.page_size
                 for r in self.replicas}
        if len(sizes) != 1:
            raise ValueError(
                f"replicas disagree on page_size {sorted(sizes)} — "
                "the routing key is per-page, all replicas must share "
                "one cache config family")
        self.policy = policy if policy is not None else FleetPolicy()
        self.logger = logger
        self._clock = clock
        self._page_size = sizes.pop()
        self._max_prompt_len = min(
            r.batcher.max_prompt_len for r in self.replicas)
        self.log = RequestLog()
        self.completions: Dict[Any, FleetCompletion] = {}
        self.rejected: Dict[Any, str] = {}          # uid -> reason
        self._queues: Dict[str, collections.deque] = {
            r.name: collections.deque() for r in self.replicas}
        self._cls: Dict[Any, str] = {}              # uid -> class name
        self._rr = 0
        self.stats = {
            "submitted": 0, "rejected": 0, "migrations": 0,
            "affinity_routed": 0,
            "routed": {r.name: 0 for r in self.replicas},
        }

    # ------------------------------------------------------------ events
    def _event(self, kind: str, **fields) -> None:
        if self.logger is not None:
            self.logger.event(kind, **fields)

    # ------------------------------------------------------------- state
    @property
    def pending(self) -> int:
        """Requests submitted but not yet completed."""
        return self.log.pending()

    def queue_depth(self, cls_name: Optional[str] = None) -> int:
        """Fleet-wide QUEUED (not yet admitted) requests, optionally
        restricted to one SLO class."""
        n = 0
        for q in self._queues.values():
            for req in q:
                if cls_name is None or self._cls[req.uid] == cls_name:
                    n += 1
        return n

    def _load(self, r: Replica) -> float:
        """Host-mirror load score — the telemetry-gauge quantities,
        read directly (no device sync, no jsonl round-trip)."""
        p = self.policy
        cfg = r.batcher.cache.config
        free_frac = (r.batcher.cache.allocator.num_free
                     / max(1, cfg.num_pages - 1))
        return (p.w_queue * len(self._queues[r.name])
                + p.w_slots * r.batcher.live_slots
                - p.w_pages * free_frac)

    # ------------------------------------------------------------- route
    def _route(self, request: Request) -> Tuple[Replica, int]:
        """Pick the serving replica; returns ``(replica,
        affinity_tokens)``.  Deterministic: ties break on replica
        order."""
        alive = [r for r in self.replicas if r.alive]
        if not alive:
            raise RuntimeError("no replica is alive")
        if self.policy.routing == "round_robin":
            r = alive[self._rr % len(alive)]
            self._rr += 1
            return r, 0
        key = (prompt_page_hashes(request.prompt, self._page_size)
               if self.policy.routing == "affinity" else [])
        best, best_score, best_aff = None, None, 0
        for i, r in enumerate(alive):
            aff = r.batcher.cache.match_len(key) if key else 0
            score = (-aff, self._load(r), i)
            if best_score is None or score < best_score:
                best, best_score, best_aff = r, score, aff
        return best, best_aff

    # ------------------------------------------------------------ submit
    def submit(self, request: Request, slo: Optional[str] = None,
               *, t_arrive: Optional[float] = None) -> bool:
        """Admission-control one request into the fleet.  Returns False
        (and emits ``request_rejected``) when the request can never be
        served (prompt + replay headroom past the prompt window, or
        more pages than any replica's pool) or its class queue is full;
        True once it is routed and logged.  ``slo`` defaults to the
        policy's first (highest-priority) class.

        The prompt-window check reserves REPLAY headroom: migration
        re-admits ``prompt + emitted`` as a prompt, so
        ``len(prompt) + max_new_tokens - 1`` must fit
        ``max_prompt_len`` — enforced here, not discovered at failover
        time."""
        cls = self.policy.cls(slo) if slo is not None \
            else self.policy.classes[0]
        cfg = self.replicas[0].batcher.cache.config
        plen = len(request.prompt)
        total = plen + request.max_new_tokens
        reason = None
        if plen + request.max_new_tokens - 1 > self._max_prompt_len:
            reason = "too_large"
        elif (total > cfg.max_len
                or cfg.tokens_to_pages(total) > cfg.num_pages - 1):
            reason = "too_large"
        elif cls.max_queue is not None and \
                self.queue_depth(cls.name) >= cls.max_queue:
            reason = "queue_full"
        if reason is not None:
            self.rejected[request.uid] = reason
            self.stats["rejected"] += 1
            self._event("request_rejected", uid=request.uid,
                        slo=cls.name, reason=reason)
            return False
        replica, aff = self._route(request)
        now = self._clock() if t_arrive is None else float(t_arrive)
        self.log.admit(request, cls.name, replica.name, now)
        self._cls[request.uid] = cls.name
        self._queues[replica.name].append(request)
        self.stats["submitted"] += 1
        self.stats["routed"][replica.name] += 1
        if aff > 0:
            self.stats["affinity_routed"] += 1
        self._event("request_routed", uid=request.uid,
                    replica=replica.name, slo=cls.name, affinity=aff)
        return True

    # -------------------------------------------------------------- step
    def _pump_order(self, name: str) -> collections.deque:
        """The replica's admission queue for this pump: class priority
        first (stable — FIFO within a class), unless the round-robin
        baseline, which is FIFO across classes too."""
        items = list(self._queues[name])
        if self.policy.routing != "round_robin":
            prio = {c.name: c.priority for c in self.policy.classes}
            items.sort(key=lambda req: prio[self._cls[req.uid]])
        return collections.deque(items)

    def step(self) -> bool:
        """One fleet scheduling turn: fire any armed fault seams,
        migrate work off dead replicas, pump every live replica one
        harvest window, absorb progress and completions into the log.
        Returns True while requests remain pending."""
        for r in self.replicas:
            if r.alive and r.fail_at is not None \
                    and r.windows >= r.fail_at:
                r.kill()
        for r in self.replicas:
            if not r.alive and (self._queues[r.name]
                                or self.log.inflight_on(r.name)):
                self._migrate(r)
        for r in self.replicas:
            if not r.alive:
                continue
            work = self._pump_order(r.name)
            if not work and r.batcher.live_slots == 0:
                continue
            r.batcher.pump(work)
            r.windows += 1
            self._queues[r.name] = work
            self._absorb(r)
        return self.pending > 0

    def drain(self, max_steps: int = 100_000
              ) -> Dict[Any, FleetCompletion]:
        """Step until nothing is pending (bounded by ``max_steps`` so a
        scheduling bug hangs a test, not a host)."""
        steps = 0
        while self.step():
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain in {max_steps} steps "
                    f"({self.pending} requests still pending)")
        return self.completions

    # ----------------------------------------------------------- absorb
    def _absorb(self, r: Replica) -> None:
        now = self._clock()
        self.log.record_progress(r.name, r.batcher.progress(), now)
        for uid, comp in r.batcher.completions.items():
            if uid in self.completions or uid not in self.log:
                continue
            e = self.log.get(uid)
            if e.done or e.replica != r.name:
                continue
            e = self.log.complete(uid, comp.tokens, comp.reason, now)
            self.completions[uid] = FleetCompletion(
                uid=uid, tokens=list(e.emitted),
                prompt_len=len(e.request.prompt),
                reason=e.reason, slo=e.slo, replica=r.name,
                replays=e.replays,
                ttft_s=(None if e.t_first is None
                        else e.t_first - e.t_arrive),
                duration_s=now - e.t_arrive,
            )

    # ---------------------------------------------------------- failover
    def _migrate(self, dead: Replica) -> None:
        """Re-admit everything a dead replica held: queued requests
        move as-is, in-flight ones replay their committed tokens as
        prompt suffix (:func:`resume_request`).  Zero requests are
        lost; uncommitted (unharvested) tokens are regenerated, not
        recovered."""
        entries = self.log.inflight_on(dead.name)
        self._queues[dead.name].clear()
        self._event("replica_dead", replica=dead.name,
                    migrated=len(entries))
        for e in entries:
            req = resume_request(e)
            target, aff = self._route(req)
            self.log.reassign(req.uid, target.name)
            self._queues[target.name].append(req)
            self.stats["migrations"] += 1
            self.stats["routed"][target.name] += 1
            self._event("request_migrated", uid=req.uid,
                        replica=target.name, replays=e.replays,
                        affinity=aff)

"""Durable write-ahead request journal: the RequestLog that survives
the process.

:mod:`apex_tpu.fleet.failover` makes a request's recoverable state
three host-side values (original request, harvested tokens, current
holder) — but its :class:`~apex_tpu.fleet.failover.RequestLog` is a
dict, so full-process death (SIGKILL, OOM, preemption of the host
itself) loses every in-flight request even though the checkpoint seam
can rebuild the *weights* bit-identically.  This module closes that
gap with the same two disciplines the PR 2 checkpoint tier uses:

- **integrity**: every journal record is one JSONL line carrying a
  ``crc`` over its canonical payload (``zlib.crc32`` — the
  checkpoint-manifest checksum), so a torn tail or a flipped bit is
  *detected*, never silently replayed;
- **atomic appends**: records land through ONE ``os.write`` on an
  ``O_APPEND`` fd (the :class:`~apex_tpu.telemetry.MetricsLogger`
  write idiom) — whole lines or nothing, no interleaving, no torn
  records from concurrent writers.

Three record kinds mirror the request lifecycle:

- ``admit`` — the full replayable identity (uid, prompt, budget,
  seed, SLO class, relative deadline), flushed IMMEDIATELY at
  submission: write-ahead means a request acknowledged to the caller
  is on disk before any serving work happens;
- ``progress`` — the harvested-token DELTA since the last record,
  with its stream ``off``set.  Progress records are buffered and
  flushed once per fleet step in one batched append (journal overhead
  must stay off the serving step's critical path — no per-token host
  work);
- ``done`` — the terminal delta plus the completion reason.

Recovery (:func:`recover_journal`) replays the lines: CRC-failed or
torn lines are skipped and counted, and a *gap* (a missing progress
record for a uid — its next record's ``off`` disagrees with the
accumulated stream) freezes that uid's recovered stream at the last
consistent prefix.  That is SAFE, not lossy: harvested tokens are a
committed prefix of a deterministic stream (the per-slot key schedule
folds absolute context length), so resuming from a shorter prefix
regenerates the missing tokens token-identically — exactly the
"harvest is the commit point" rule the in-process failover already
lives by.  ``FleetRouter.resume_from_journal`` turns the recovery into
re-admissions; reuse ONE journal path across restarts so later
recoveries still see the original admit records.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

from apex_tpu.serving.serve import Request

__all__ = ["RequestJournal", "JournalRecovery", "recover_journal"]

#: uid types a journal can round-trip through JSON as dict keys
_UID_TYPES = (str, int)


def _canon(payload: Dict[str, Any]) -> bytes:
    """The canonical encoding the CRC covers: sorted keys, no
    whitespace — byte-stable across write and recovery."""
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def _seal(payload: Dict[str, Any]) -> str:
    """One journal line: the payload plus its CRC."""
    rec = dict(payload)
    rec["crc"] = zlib.crc32(_canon(payload)) & 0xFFFFFFFF
    return json.dumps(rec, sort_keys=True, separators=(",", ":")) + "\n"


class RequestJournal:
    """Write-ahead JSONL journal for a fleet's request state.

    The router drives it: :meth:`admit` at submission (flushed before
    the submit returns), :meth:`sync` once per fleet step (buffers
    every entry's harvested-token delta and terminal state, then ONE
    batched ``os.write``).  ``stats`` self-times the write path —
    ``write_s`` against the fleet's serving wall time is the < 2%%
    overhead gate the chaos dryrun asserts."""

    def __init__(self, path: str, logger: Optional[Any] = None):
        self.path = str(path)
        self.logger = logger
        self._fd: Optional[int] = None
        self._fd_lock = threading.Lock()
        self._buf: List[str] = []
        #: uid -> stream length already journaled
        self._state: Dict[Any, int] = {}
        self._done: set = set()
        self.stats = {"appends": 0, "records": 0, "bytes": 0,
                      "write_s": 0.0}

    # ------------------------------------------------------------ write
    def _append(self, data: str) -> None:
        """One atomic append: O_APPEND + a single write, so records
        are whole lines on disk no matter who else appends."""
        t0 = time.perf_counter()
        with self._fd_lock:
            if self._fd is None:
                self._fd = os.open(
                    self.path,
                    os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            payload = data.encode("utf-8")
            os.write(self._fd, payload)
        self.stats["appends"] += 1
        self.stats["bytes"] += len(payload)
        self.stats["write_s"] += time.perf_counter() - t0

    def flush(self) -> None:
        """Land every buffered record in one append."""
        if not self._buf:
            return
        lines, self._buf = self._buf, []
        self.stats["records"] += len(lines)
        self._append("".join(lines))

    def close(self) -> None:
        self.flush()
        with self._fd_lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    # ----------------------------------------------------------- records
    def admit(self, entry: Any) -> None:
        """Journal one admission write-ahead: the record is on disk
        before the request is served.  ``entry`` is the failover log's
        :class:`~apex_tpu.fleet.failover.LogEntry`."""
        req = entry.request
        if not isinstance(req.uid, _UID_TYPES):
            raise ValueError(
                f"journaled uids must be str or int (JSON-stable), "
                f"got {type(req.uid).__name__}: {req.uid!r}")
        self._buf.append(_seal({
            "k": "admit",
            "uid": req.uid,
            "prompt": [int(t) for t in req.prompt],
            "budget": int(req.max_new_tokens),
            "seed": None if req.seed is None else int(req.seed),
            "slo": entry.slo,
            "t": float(entry.t_arrive),
            "deadline_s": (None if entry.deadline_rel is None
                           else float(entry.deadline_rel)),
        }))
        self._state[req.uid] = 0
        self.flush()

    def handoff(self, uid: Any, src: str, dst: str) -> None:
        """Journal a page-level ownership transfer write-ahead: the
        record lands BEFORE any pages move, so a crash mid-transfer
        recovers a request that is at worst back on the recompute path
        (its admit + progress records still replay the stream
        token-identically).  Recovery ignores the record itself —
        replica names do not survive a restart — it exists for the
        durability ordering and for post-mortem forensics."""
        self._buf.append(_seal({
            "k": "handoff", "uid": uid, "src": str(src),
            "dst": str(dst)}))
        self.flush()

    def sync(self, log: Any) -> None:
        """Fold the in-memory :class:`RequestLog` into the journal:
        one progress/terminal delta per entry that moved, ONE batched
        append for the whole step."""
        for e in log.entries():
            uid = e.request.uid
            n = self._state.get(uid)
            if n is None or uid in self._done:
                continue
            if e.done:
                delta = e.emitted[n:]
                self._buf.append(_seal({
                    "k": "done", "uid": uid, "off": n,
                    "toks": [int(t) for t in delta],
                    "reason": e.reason,
                }))
                self._done.add(uid)
                self._state[uid] = len(e.emitted)
            elif len(e.emitted) > n:
                delta = e.emitted[n:]
                self._buf.append(_seal({
                    "k": "progress", "uid": uid, "off": n,
                    "toks": [int(t) for t in delta],
                }))
                self._state[uid] = len(e.emitted)
        self.flush()

    def prime(self, log: Any) -> None:
        """Seed the journal's in-memory cursor from a log rebuilt by
        :func:`recover_journal` WITHOUT re-writing records (their
        admits and deltas are already on disk): subsequent
        :meth:`sync` calls journal only NEW tokens.  Call it after
        ``FleetRouter.resume_from_journal`` when the restarted process
        appends to the same journal path."""
        for e in log.entries():
            uid = e.request.uid
            self._state[uid] = len(e.emitted)
            if e.done:
                self._done.add(uid)


@dataclasses.dataclass
class JournalRecovery:
    """What :func:`recover_journal` rebuilt from disk.

    ``entries`` maps uid to a dict with the recovered ``request``
    (the ORIGINAL — prompt/budget/seed as admitted), ``slo``,
    ``deadline_s`` (relative, re-armed on resume), the committed
    ``emitted`` stream, and ``done``/``reason``.  ``corrupt`` counts
    CRC-failed or torn lines (skipped), ``gapped`` counts uids whose
    stream was frozen at the last consistent prefix because a delta
    record was lost — both recover token-identically, the latter by
    regeneration."""

    entries: Dict[Any, Dict[str, Any]]
    records: int = 0
    corrupt: int = 0
    gapped: int = 0

    @property
    def inflight(self) -> Dict[Any, Dict[str, Any]]:
        return {u: i for u, i in self.entries.items() if not i["done"]}

    @property
    def completed(self) -> Dict[Any, Dict[str, Any]]:
        return {u: i for u, i in self.entries.items() if i["done"]}


def recover_journal(path: str) -> JournalRecovery:
    """Replay a journal file into per-uid recovered state.

    Tolerant by design: unparseable or CRC-failed lines (torn tail,
    bit flip) are skipped and counted; a uid whose next delta's
    ``off`` disagrees with its accumulated stream is marked gapped and
    frozen at the consistent prefix (later records for it are
    ignored — stitching across a hole would corrupt the stream, while
    regenerating from the prefix is exact).  A missing file recovers
    to an empty journal."""
    rec = JournalRecovery(entries={})
    gapped: set = set()
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return rec
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            obj = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            rec.corrupt += 1
            continue
        if not isinstance(obj, dict) or "crc" not in obj:
            rec.corrupt += 1
            continue
        crc = obj.pop("crc")
        if zlib.crc32(_canon(obj)) & 0xFFFFFFFF != crc:
            rec.corrupt += 1
            continue
        rec.records += 1
        kind = obj.get("k")
        uid = obj.get("uid")
        if kind == "admit":
            if uid in rec.entries:
                continue                    # duplicate admit: first wins
            rec.entries[uid] = {
                "request": Request(
                    uid=uid, prompt=list(obj["prompt"]),
                    max_new_tokens=int(obj["budget"]),
                    seed=obj.get("seed")),
                "slo": obj.get("slo"),
                "deadline_s": obj.get("deadline_s"),
                "t_arrive": obj.get("t"),
                "emitted": [],
                "done": False,
                "reason": None,
            }
        elif kind in ("progress", "done"):
            info = rec.entries.get(uid)
            if info is None or info["done"] or uid in gapped:
                continue
            if obj.get("off") != len(info["emitted"]):
                gapped.add(uid)
                rec.gapped += 1
                continue
            info["emitted"].extend(int(t) for t in obj["toks"])
            if kind == "done":
                info["done"] = True
                info["reason"] = obj.get("reason")
    return rec

"""Profiling: named traces + XLA cost analysis.

Capability match of ``apex.pyprof`` (reference: apex/pyprof/ — 3 stages:
(1) nvtx monkey-patch markers, nvmarker.py:27-110; (2) nvprof SQLite
parsing; (3) per-kernel FLOP/byte classification across 27 op-class
modules).  The TPU workflow replaces all three:

1. **markers** → :func:`annotate` / :func:`trace_region` emit XLA
   metadata (``jax.named_scope``) and profiler annotations that show up
   in xplane/tensorboard traces;
2. **parse**   → :func:`trace` captures an xplane trace directory that
   tensorboard / xprof reads directly (no SQLite step);
3. **prof**    → :func:`cost_analysis` asks XLA's analytical cost model
   for FLOPs and bytes of a jitted function — the compiler already
   classifies every fused op, so the 27 hand-written op-class modules
   reduce to one call; :func:`summarize` turns it into the
   FLOPs/bytes/intensity report the reference's ``prof`` stage prints.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Callable, Dict, Optional

import jax

from apex_tpu.pyprof.parse import op_table, parse  # noqa: E402,F401
from apex_tpu.pyprof.prof import (  # noqa: E402,F401
    OP_CLASSES,
    classify,
    prof,
    prof_table,
    utilization,
)

__all__ = [
    "annotate",
    "trace_region",
    "trace",
    "parse",
    "op_table",
    "classify",
    "prof",
    "prof_table",
    "utilization",
    "OP_CLASSES",
    "cost_analysis",
    "summarize",
    "Timers",
]


def annotate(fn: Optional[Callable] = None, name: Optional[str] = None):
    """Decorator adding a named scope visible in traces and HLO
    (the analog of pyprof.nvtx wrapping, reference: nvmarker.py:67-108 —
    opt-in per function instead of patching every torch call)."""

    def deco(f):
        label = name or getattr(f, "__name__", "fn")

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with jax.named_scope(label):
                return f(*args, **kwargs)

        return wrapper

    if fn is None:
        return deco
    return deco(fn)


@contextlib.contextmanager
def trace_region(name: str):
    """Context-manager form of :func:`annotate` + host-side profiler
    annotation."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an xplane trace (open with tensorboard's profile plugin —
    the nvprof/nvvp replacement)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """XLA's analytical cost model for ``jit(fn)(*args)``:
    flops, bytes accessed, and per-category breakdown when available."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, (list, tuple)):  # older jax returns [dict]
        costs = costs[0] if costs else {}
    return dict(costs or {})


def summarize(fn: Callable, *args, peak_flops: Optional[float] = None,
              peak_bandwidth: Optional[float] = None, **kwargs) -> dict:
    """FLOPs / bytes / arithmetic-intensity report (the reference's
    ``prof`` output: per-op efficiency tables, apex/pyprof/prof/).  With
    ``peak_*`` given, adds roofline utilization bounds."""
    from apex_tpu.pyprof.prof import _cost_numbers

    costs = cost_analysis(fn, *args, **kwargs)
    flops, bytes_accessed = _cost_numbers(costs)
    out = {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arithmetic_intensity": flops / bytes_accessed
        if bytes_accessed else float("inf"),
    }
    if peak_flops and peak_bandwidth and bytes_accessed:
        t_compute = flops / peak_flops
        t_memory = bytes_accessed / peak_bandwidth
        out["compute_bound"] = t_compute >= t_memory
        out["min_time_s"] = max(t_compute, t_memory)
    return out


class Timers:
    """Named wall timers with device sync
    (reference: apex/transformer/pipeline_parallel/_timers.py:5-83 —
    cuda.synchronize becomes block_until_ready on the last output)."""

    class _Timer:
        def __init__(self, name):
            self.name = name
            self.elapsed_ = 0.0
            self.started = False
            self._start = 0.0

        def start(self, barrier_on: Any = None):
            assert not self.started, f"timer {self.name} already started"
            if barrier_on is not None:
                jax.block_until_ready(barrier_on)
            self._start = time.perf_counter()
            self.started = True

        def stop(self, barrier_on: Any = None):
            assert self.started, f"timer {self.name} not started"
            if barrier_on is not None:
                jax.block_until_ready(barrier_on)
            self.elapsed_ += time.perf_counter() - self._start
            self.started = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started = False

        def elapsed(self, reset: bool = True) -> float:
            e = self.elapsed_
            if reset:
                self.reset()
            return e

    def __init__(self):
        self.timers: Dict[str, Timers._Timer] = {}

    def __call__(self, name: str) -> "Timers._Timer":
        if name not in self.timers:
            self.timers[name] = self._Timer(name)
        return self.timers[name]

    def log(self, names=None, normalizer: float = 1.0) -> str:
        names = names or list(self.timers)
        parts = [
            f"{n}: {self.timers[n].elapsed(reset=False) * 1000.0 / normalizer:.2f}"
            for n in names if n in self.timers
        ]
        return "time (ms) | " + " | ".join(parts)

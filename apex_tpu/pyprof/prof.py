"""Per-op-class report over a parsed trace — the reference's ``prof`` stage.

The reference maps every captured kernel to one of 27 op-class modules
that know its semantics (reference: apex/pyprof/prof/ — blas.py, conv.py,
optim.py, reduction.py, ...) and prints a per-op table with FLOPs/bytes.
On TPU the kernel namespace is XLA's HLO opcode set (plus Pallas
custom-calls), so the classifier keys on HLO names instead of CUDA kernel
mangles; class semantics (whether a class does MXU work, moves bytes, or
is a collective) drive the utilization columns.

Typical use::

    with pyprof.trace(log_dir):
        step(...)
    rows = pyprof.parse(log_dir, plane_filter="TPU")
    classes = pyprof.prof(rows)
    print(pyprof.prof_table(classes))
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

__all__ = ["classify", "prof", "prof_table", "utilization", "OP_CLASSES"]


# Each entry: (class name, regex over the normalized op name, kind).
# kind ∈ {"compute", "memory", "collective", "host", "other"} — the
# TPU-roofline role the class plays (MXU FLOPs / HBM bytes / ICI).
# Order matters: first match wins.  The taxonomy mirrors the reference's
# op-class split (reference: apex/pyprof/prof/ 27 modules) collapsed onto
# the HLO opcode set.
OP_CLASSES = (
    ("flash_attention", r"flash|attention", "compute"),
    ("pallas_kernel", r"pallas|custom-call|custom_call|mosaic", "compute"),
    ("gemm", r"\bdot|gemm|matmul|einsum", "compute"),
    ("convolution", r"conv(?!ert)", "compute"),
    ("cholesky_triangular", r"cholesky|triangular", "compute"),
    ("all_reduce", r"all-reduce|all_reduce|psum", "collective"),
    ("all_gather", r"all-gather|all_gather", "collective"),
    ("reduce_scatter", r"reduce-scatter|reduce_scatter", "collective"),
    ("all_to_all", r"all-to-all|all_to_all", "collective"),
    ("permute", r"collective-permute|ppermute|collective_permute",
     "collective"),
    ("host_transfer", r"infeed|outfeed|host|transfer|\bsend\b|\brecv\b",
     "host"),
    ("loop_control", r"\bwhile\b|conditional|checkpoint|remat|closed_call",
     "compute"),
    ("sort", r"sort|top-k|topk", "compute"),
    ("rng", r"\brng\b|threefry|random|philox", "compute"),
    ("slice_update", r"dynamic-slice|dynamic_slice|dynamic-update|"
     r"dynamic_update|slice|pad", "memory"),
    ("reduction", r"reduce|cumsum|cumulative", "compute"),
    # word-bound and AFTER the specific classes: select-and-scatter and
    # gather-bearing fusion names must not misfile here (ADVICE r3)
    ("scatter_gather", r"(?<!and-)\bscatter\b|\bgather\b", "memory"),
    ("normalization", r"norm|batch-norm|batch_norm", "compute"),
    ("copy_layout", r"copy|transpose|reshape|bitcast|broadcast|concat|"
     r"reverse|tuple|convert", "memory"),
    ("select_compare", r"select|compare|clamp|where|iota", "memory"),
    # long opcode forms listed explicitly; short/collision-prone tokens
    # are fully word-bounded so host frames ("ThunkExecutor::Execute",
    # "absl::Mutex", "Notification") never misfile as device work
    ("elementwise",
     r"multiply|divide|exponential|logarithm|subtract|negate|maximum|"
     r"minimum|remainder|rsqrt|sqrt|tanh|floor|ceil|sine|cosine|power|"
     r"logistic|sigmoid|gelu|relu|erf\b|"
     r"\b(add|sub|mul|div|exp|log|pow|neg|abs|max|min|and|or|xor|not|"
     r"sin|cos|sign)\b", "memory"),
    ("fusion", r"fusion|\bcall\b", "compute"),
)

# an HLO trace event name is often the full instruction text
# ("%copy-start.5 = (bf16[8,8,1024,128]{...} ...") — the opcode is the
# LHS symbol, so classification must never look past " = "
_NORM = re.compile(r"^%?([a-zA-Z0-9_.\-]+?)(\.\d+)?$")


def classify(name: str) -> tuple:
    """→ (op_class, kind) for one HLO/kernel event name."""
    base = name.strip().split(" = ", 1)[0].strip()
    m = _NORM.match(base)
    base = (m.group(1) if m else base).lower()
    for cls, pat, kind in OP_CLASSES:
        if re.search(pat, base):
            return cls, kind
    return "other", "other"


#: trace lines that carry whole-program / per-step envelope events — a
#: per-op report must not double-count them against the op rows.
#: Exact (lowercased) XLA line names; substring matching would silently
#: drop user lines that merely contain "step"
_ENVELOPE_LINES = ("xla modules", "steps", "framework name scope")


def prof(
    rows: List[Dict[str, Any]], include_envelopes: bool = False
) -> List[Dict[str, Any]]:
    """Aggregate :func:`apex_tpu.pyprof.parse` rows into per-class rows.

    Returns rows sorted by total time::

        {"op_class", "kind", "count", "ops", "total_ms", "avg_ms", "pct"}

    ``ops`` is the distinct member-op names (up to 8, by time), the
    breadcrumb back to the per-op table.  Rows from "XLA Modules" /
    "Steps" trace lines (whole-program envelopes that would double-count
    every op) are dropped unless ``include_envelopes``.
    """
    agg: Dict[str, Dict[str, Any]] = {}
    for r in rows:
        line = str(r.get("line", "")).lower()
        if not include_envelopes and line in _ENVELOPE_LINES:
            continue
        cls, kind = classify(r["name"])
        row = agg.setdefault(cls, {
            "op_class": cls, "kind": kind, "count": 0, "total_ms": 0.0,
            "_members": {},
        })
        row["count"] += r["count"]
        row["total_ms"] += r["total_ms"]
        row["_members"][r["name"]] = (
            row["_members"].get(r["name"], 0.0) + r["total_ms"]
        )
    out = sorted(agg.values(), key=lambda r: -r["total_ms"])
    total = sum(r["total_ms"] for r in out) or 1.0
    for r in out:
        members = sorted(r.pop("_members").items(), key=lambda kv: -kv[1])
        r["ops"] = [k for k, _ in members[:8]]
        r["avg_ms"] = r["total_ms"] / max(r["count"], 1)
        r["pct"] = 100.0 * r["total_ms"] / total
    return out


def _cost_numbers(costs: Dict[str, float]) -> tuple:
    """(flops, bytes accessed) from an XLA cost-analysis dict — shared
    with :func:`apex_tpu.pyprof.summarize` so a cost-key rename cannot
    silently zero one of the two call sites."""
    return (
        float(costs.get("flops", 0.0)),
        float(costs.get("bytes accessed", 0.0)),
    )


def _time_by_kind(classes: List[Dict[str, Any]]) -> Dict[str, float]:
    by_kind: Dict[str, float] = {}
    for r in classes:
        by_kind[r["kind"]] = by_kind.get(r["kind"], 0.0) + r["total_ms"]
    return by_kind


def prof_table(classes: List[Dict[str, Any]], top: Optional[int] = None) -> str:
    """Format prof() rows — the reference's per-op-class summary print."""
    lines = [
        f"{'class':<20} {'kind':<11} {'count':>7} {'total ms':>10} "
        f"{'%':>6}  top ops"
    ]
    for r in classes[:top]:
        ops = ", ".join(r["ops"][:3])
        lines.append(
            f"{r['op_class']:<20} {r['kind']:<11} {r['count']:>7} "
            f"{r['total_ms']:>10.3f} {r['pct']:>6.1f}  {ops[:60]}"
        )
    by_kind = _time_by_kind(classes)
    total = sum(by_kind.values()) or 1.0
    split = "  ".join(
        f"{k}: {100.0 * v / total:.1f}%" for k, v in
        sorted(by_kind.items(), key=lambda kv: -kv[1])
    )
    lines.append(f"-- time by kind: {split}")
    return "\n".join(lines)


def utilization(
    classes: List[Dict[str, Any]],
    costs: Dict[str, float],
    peak_flops: Optional[float] = None,
    peak_bandwidth: Optional[float] = None,
    steps: int = 1,
) -> Dict[str, Any]:
    """Marry the per-class time split with XLA cost analysis — the
    reference ``prof`` stage's FLOPs/bytes/efficiency columns
    (reference: apex/pyprof/prof/ op-class compute of flops, bytes and
    silicon efficiency per kernel).

    ``classes``: :func:`prof` output for a trace of ``steps`` executions;
    ``costs``: :func:`apex_tpu.pyprof.cost_analysis` of the traced fn
    (per single execution).  Returns compute/memory time, achieved
    FLOP/s and bytes/s, and — when peaks are given — utilization
    fractions.
    """
    by_kind = _time_by_kind(classes)
    compute_s = by_kind.get("compute", 0.0) / 1e3 / max(steps, 1)
    memory_s = by_kind.get("memory", 0.0) / 1e3 / max(steps, 1)
    # bandwidth follows the roofline convention: bytes over total DEVICE
    # time (compute + memory + collective — compute-class ops move most
    # of the HBM bytes; dividing by memory-class time alone would
    # inflate past 1.0, and folding host/other time in would deflate it)
    total_s = sum(
        by_kind.get(k, 0.0) for k in ("compute", "memory", "collective")
    ) / 1e3 / max(steps, 1)
    other_s = (
        sum(by_kind.values()) / 1e3 / max(steps, 1) - total_s
    )
    flops, bytes_accessed = _cost_numbers(costs)
    out: Dict[str, Any] = {
        "compute_ms": round(compute_s * 1e3, 3),
        "memory_ms": round(memory_s * 1e3, 3),
        "collective_ms": round(
            by_kind.get("collective", 0.0) / max(steps, 1), 3
        ),
        "total_ms": round(total_s * 1e3, 3),
        "host_other_ms": round(other_s * 1e3, 3),
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "achieved_flops_per_sec": flops / compute_s if compute_s else 0.0,
        "achieved_bytes_per_sec": (
            bytes_accessed / total_s if total_s else 0.0
        ),
    }
    if peak_flops and compute_s:
        out["compute_utilization"] = round(
            out["achieved_flops_per_sec"] / peak_flops, 4
        )
    if peak_bandwidth and total_s:
        out["bandwidth_utilization"] = round(
            out["achieved_bytes_per_sec"] / peak_bandwidth, 4
        )
    return out

"""xplane trace → per-op table (the reference's ``pyprof.parse`` stage).

The reference parses nvprof's SQLite database into per-kernel records
(reference: apex/pyprof/parse/parse.py + db.py/kernel.py/nvvp.py) that its
``prof`` stage turns into per-op tables.  The TPU equivalent consumes the
xplane protobuf the JAX profiler writes (``<log_dir>/plugins/profile/...
*.xplane.pb``) and aggregates device events into (name, count, total ms,
%) rows — no tensorflow dependency: the few XSpace fields needed are read
with a minimal protobuf wire-format reader.

Field numbers (tsl/profiler/protobuf/xplane.proto, verified against
traces this code ships tests for):
  XSpace.planes = 1
  XPlane.name = 2, .lines = 3, .event_metadata = 4 (map<id, XEventMetadata>)
  XLine.name = 2, .events = 4
  XEvent.metadata_id = 1, .duration_ps = 3
  XEventMetadata.id = 1, .name = 2
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["parse", "op_table"]


# ---------------------------------------------------------------------------
# minimal protobuf wire reader
# ---------------------------------------------------------------------------


def _varint(b: bytes, i: int) -> Tuple[int, int]:
    r = 0
    s = 0
    while True:
        x = b[i]
        i += 1
        r |= (x & 0x7F) << s
        if not x & 0x80:
            return r, i
        s += 7


def _fields(b: bytes) -> Iterable[Tuple[int, int, Any]]:
    i = 0
    n = len(b)
    while i < n:
        tag, i = _varint(b, i)
        f, w = tag >> 3, tag & 7
        if w == 0:
            v, i = _varint(b, i)
        elif w == 2:
            ln, i = _varint(b, i)
            v = b[i : i + ln]
            i += ln
        elif w == 1:
            v = b[i : i + 8]
            i += 8
        elif w == 5:
            v = b[i : i + 4]
            i += 4
        else:  # unknown wire type: cannot continue safely
            return
        yield f, w, v


def _first(msg: bytes, field: int, default=None):
    for f, _, v in _fields(msg):
        if f == field:
            return v
    return default


# ---------------------------------------------------------------------------
# xplane walk
# ---------------------------------------------------------------------------


def _iter_planes(space: bytes):
    for f, w, v in _fields(space):
        if f == 1 and w == 2:
            yield v


def _event_metadata(plane: bytes) -> Dict[int, str]:
    meta: Dict[int, str] = {}
    for f, w, v in _fields(plane):
        if f == 4 and w == 2:  # map entry {key=1, value=XEventMetadata}
            key = _first(v, 1, 0)
            em = _first(v, 2, b"")
            name = _first(em, 2, b"")
            if isinstance(name, bytes):
                meta[key] = name.decode("utf-8", "replace")
    return meta


def parse(
    log_dir: str,
    plane_filter: Optional[str] = None,
    line_filter: Optional[str] = None,
    exclude_prefixes: Tuple[str, ...] = ("end: ", "$"),
) -> List[Dict[str, Any]]:
    """Aggregate a captured trace into per-op rows.

    ``log_dir`` is the directory given to :func:`apex_tpu.pyprof.trace`.
    Optional ``plane_filter`` / ``line_filter`` are case-insensitive
    substring matches (e.g. ``plane_filter="TPU"``); by default every
    plane/line is read.  Events whose names start with one of
    ``exclude_prefixes`` are skipped (python-frame markers and paired
    ``end:`` markers, which would double-count).

    Returns rows sorted by total time, each::

        {"name", "count", "total_ms", "avg_ms", "pct", "plane", "line"}

    ``pct`` is relative to the summed duration of the *included* events.
    """
    paths = sorted(glob.glob(
        os.path.join(log_dir, "**", "*.xplane.pb"), recursive=True
    ))
    if not paths:
        raise FileNotFoundError(
            f"no *.xplane.pb under {log_dir!r} — did the trace() context "
            "complete?"
        )
    agg: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
    for path in paths:
        with open(path, "rb") as fh:
            space = fh.read()
        for plane in _iter_planes(space):
            pname_b = _first(plane, 2, b"")
            pname = pname_b.decode("utf-8", "replace")
            if plane_filter and plane_filter.lower() not in pname.lower():
                continue
            meta = _event_metadata(plane)
            for f, w, line in _fields(plane):
                if f != 3 or w != 2:
                    continue
                lname = _first(line, 2, b"")
                lname = (
                    lname.decode("utf-8", "replace")
                    if isinstance(lname, bytes) else str(lname)
                )
                if line_filter and line_filter.lower() not in lname.lower():
                    continue
                for ef, ew, ev in _fields(line):
                    if ef != 4 or ew != 2:
                        continue
                    mid = _first(ev, 1, 0)
                    dur = _first(ev, 3, 0)
                    name = meta.get(mid, f"<metadata {mid}>")
                    if any(name.startswith(p) for p in exclude_prefixes):
                        continue
                    key = (pname, lname, name)
                    row = agg.setdefault(key, {
                        "name": name, "plane": pname, "line": lname,
                        "count": 0, "total_ms": 0.0,
                    })
                    row["count"] += 1
                    row["total_ms"] += (dur or 0) / 1e9  # ps → ms
    rows = sorted(agg.values(), key=lambda r: -r["total_ms"])
    total = sum(r["total_ms"] for r in rows) or 1.0
    for r in rows:
        r["avg_ms"] = r["total_ms"] / max(r["count"], 1)
        r["pct"] = 100.0 * r["total_ms"] / total
    return rows


def op_table(rows: List[Dict[str, Any]], top: int = 25) -> str:
    """Format parse() rows the way the reference's ``prof`` stage prints
    its per-op table."""
    lines = [
        f"{'op':<48} {'count':>6} {'total ms':>10} {'avg ms':>9} {'%':>6}"
    ]
    for r in rows[:top]:
        lines.append(
            f"{r['name'][:48]:<48} {r['count']:>6} "
            f"{r['total_ms']:>10.3f} {r['avg_ms']:>9.3f} {r['pct']:>6.1f}"
        )
    return "\n".join(lines)

"""Weight-norm reparameterization.

Capability match of ``apex.reparameterization``
(reference: apex/reparameterization/reparameterization.py:4,
weight_norm.py:22 — module hooks rewriting ``weight`` from (g, v) before
every forward, with a fused CUDA norm kernel in csrc).  Functionally:
``w = g * v / ||v||`` over the chosen dim, as a pair of pure converters
on a param pytree — apply ``compute_weight`` inside the forward (jit
fuses the norm), no hooks needed.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

__all__ = ["weight_norm_init", "compute_weight", "remove_weight_norm",
           "apply_weight_norm"]


def _norm_except(v: jnp.ndarray, dim: int) -> jnp.ndarray:
    """||v|| reduced over every axis except ``dim`` (reference:
    weight_norm.py ``norm_except_dim`` semantics; dim=None → full norm)."""
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes,
                            keepdims=True))


def weight_norm_init(weight: jnp.ndarray, dim: int = 0) -> dict:
    """Split a weight into the (g, v) parameterization."""
    norm = _norm_except(weight, dim)
    return {"g": norm.astype(weight.dtype), "v": weight}


def compute_weight(wn: dict, dim: int = 0) -> jnp.ndarray:
    """w = g * v/||v|| (reference: weight_norm.py ``compute_weight``)."""
    v = wn["v"]
    norm = _norm_except(v, dim)
    w = wn["g"].astype(jnp.float32) * v.astype(jnp.float32) / jnp.maximum(
        norm, 1e-12
    )
    return w.astype(v.dtype)


def remove_weight_norm(wn: dict, dim: int = 0) -> jnp.ndarray:
    """Collapse (g, v) back to a plain weight (reference:
    ``remove_weight_norm``)."""
    return compute_weight(wn, dim)


def apply_weight_norm(params: Any, name: str = "weight", dim: int = 0) -> Any:
    """Convert every ``name`` leaf in a param pytree to the (g, v) form
    (the analog of recursively hooking modules, reference:
    apply_weight_norm with module=None)."""

    def convert(path, leaf):
        if path and str(getattr(path[-1], "key", path[-1])) == name:
            return weight_norm_init(leaf, dim)
        return leaf

    return jax.tree_util.tree_map_with_path(convert, params)

"""Version-portability shims for jax symbols the framework uses inside
``shard_map`` bodies.

The pinned/newer jax exposes ``lax.axis_size`` and ``lax.pcast``; jax
0.4.x (still common on CI hosts) has neither.  One shim module keeps
every call site identical across versions instead of scattering
``hasattr`` guards:

- :func:`axis_size` — static axis extent.  Under 0.4.x shard_map,
  ``psum(1, axis)`` of a python literal constant-folds to a static
  python int, so it is usable in shape arithmetic on both versions.
- :func:`pcast` — varying/invariant cast of the vma type system.
  0.4.x has no vma typing, so the cast is a numeric identity there
  (autodiff under its ``check_rep`` model already keeps per-device
  grads local, which is what ``to="varying"`` exists to force).
"""

from __future__ import annotations

import jax

__all__ = ["axis_size", "pcast"]


def axis_size(axis_name):
    """Static extent of a bound mesh axis (or tuple product)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast(x, axis_name, *, to):
    """vma cast; identity where the vma system does not exist."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to=to)
    return x

"""Version-portability shims for jax symbols the framework uses inside
``shard_map`` bodies.

The pinned/newer jax exposes ``lax.axis_size`` and ``lax.pcast``; jax
0.4.x (still common on CI hosts) has neither.  One shim module keeps
every call site identical across versions instead of scattering
``hasattr`` guards:

- :func:`axis_size` — static axis extent.  Under 0.4.x shard_map,
  ``psum(1, axis)`` of a python literal constant-folds to a static
  python int, so it is usable in shape arithmetic on both versions.
- :func:`pcast` — varying/invariant cast of the vma type system.
  0.4.x has no vma typing, so the cast is a numeric identity there
  (autodiff under its ``check_rep`` model already keeps per-device
  grads local, which is what ``to="varying"`` exists to force).
- :func:`shard_map` — the SPMD map itself.  ``jax.shard_map`` landed
  after 0.4.x, whose spelling is ``jax.experimental.shard_map`` with a
  ``check_rep`` flag where the newer API has ``check_vma``; the shim
  takes the common ``(f, mesh, in_specs, out_specs)`` call the
  examples and tools use and maps ``check=False`` onto whichever flag
  the installed jax understands.
"""

from __future__ import annotations

import jax

__all__ = ["axis_size", "pcast", "shard_map"]


def axis_size(axis_name):
    """Static extent of a bound mesh axis (or tuple product)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast(x, axis_name, *, to):
    """vma cast; identity where the vma system does not exist."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to=to)
    return x


def shard_map(f, mesh, in_specs, out_specs, check: bool = True):
    """Version-portable ``shard_map(f, mesh=..., in_specs=...,
    out_specs=...)``.  ``check=False`` disables the vma checker on
    jax >= 0.6.  On 0.4.x the legacy ``check_rep`` checker is ALWAYS
    disabled: its replication inference cannot see through the
    master-weight optimizer update or ring-attention's ``lax.cond``
    shard skipping and rejects valid programs the newer checker
    accepts (the same accommodation bench.py's gradsync child and
    tools/profile_r05.py already make)."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        kw = {} if check else {"check_vma": False}
        return fn(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs,
               out_specs=out_specs, check_rep=False)

"""Transducer (RNN-T) joint and loss.

Capability match of ``apex.contrib.transducer``
(reference: apex/contrib/transducer/transducer.py — ``TransducerJoint``
:5, ``TransducerLoss`` :68; kernels in apex/contrib/csrc/transducer/).

- The joint's broadcast add ``f[:,t] + g[:,u]`` fuses under XLA; the
  reference's packed-input path (dropping pad positions to save memory)
  is replaced by masking — dynamic shapes would defeat jit, and padded
  lanes are free on the VPU.
- The loss is the exact RNN-T forward algorithm (alpha recursion in log
  space) written with ``lax.scan`` over time; its backward comes from
  autodiff of the recursion, which reproduces the fused
  softmax-gradient trick's math (the reference fuses d(loss)/d(logits)
  with the softmax backward to save one V-sized tensor).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_loss"]

_NEG = -1e30


class TransducerJoint:
    """h[b,t,u] = f[b,t] + g[b,u] (+ relu, + dropout)
    (reference: transducer.py:5-66 ``TransducerJoint``)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: float = 0.0):
        if pack_output:
            raise NotImplementedError(
                "packed output is a CUDA memory optimization; on TPU use "
                "the dense (masked) layout"
            )
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f: jnp.ndarray, g: jnp.ndarray,
                 f_len: Optional[jnp.ndarray] = None,
                 g_len: Optional[jnp.ndarray] = None,
                 rng: Optional[jax.Array] = None) -> jnp.ndarray:
        h = f[:, :, None, :] + g[:, None, :, :]
        if self.relu:
            h = jax.nn.relu(h)
        if self.dropout > 0.0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.dropout, h.shape)
            h = jnp.where(keep, h / (1.0 - self.dropout), 0.0)
        return h


def transducer_loss(
    logits: jnp.ndarray,
    targets: jnp.ndarray,
    f_len: jnp.ndarray,
    y_len: jnp.ndarray,
    blank_idx: int = 0,
) -> jnp.ndarray:
    """RNN-T negative log-likelihood per example.

    ``logits``: (B, T, U+1, V); ``targets``: (B, U) label ids;
    ``f_len``: (B,) valid time steps; ``y_len``: (B,) valid labels.
    (reference: transducer.py:68-195 ``TransducerLoss``)
    """
    b, t_max, u1, v = logits.shape
    u_max = u1 - 1
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # blank/emit probabilities per lattice node
    blank = logp[..., blank_idx]  # (B, T, U+1)
    emit = jnp.take_along_axis(
        logp[:, :, :u_max, :],
        targets[:, None, :, None].astype(jnp.int32),
        axis=-1,
    )[..., 0]  # (B, T, U)
    # mask invalid label positions
    upos = jnp.arange(u_max)
    emit = jnp.where(upos[None, None, :] < y_len[:, None, None], emit, _NEG)

    # alpha over the (T, U+1) lattice: alpha[t,u] =
    #   logaddexp(alpha[t-1,u] + blank[t-1,u], alpha[t,u-1] + emit[t,u-1]);
    # the within-row (label) recursion a[u] = logaddexp(h[u], a[u-1]+m[u])
    # is a log-space linear recurrence solved with an associative scan,
    # so each time row costs O(log U) depth instead of a U-length loop.

    def combine(x, y):
        # elements are affine maps a → logaddexp(add, a + mul)
        xa, xm = x
        ya, ym = y
        return jnp.logaddexp(ya, xa + ym), xm + ym

    def row_update(horiz, emit_row):
        mul = jnp.concatenate(
            [jnp.zeros((b, 1)), emit_row], axis=1
        )  # mul[0] unused: u=0 has no left neighbour
        out, _ = lax.associative_scan(combine, (horiz, mul), axis=1)
        return out

    alpha0 = jnp.full((b, u1), _NEG).at[:, 0].set(0.0)
    alpha = row_update(alpha0, emit[:, 0, :])  # row t=0: vertical only

    def time_step(alpha, x):
        blank_t, emit_t = x  # blank of row t-1, emit of row t
        new_alpha = row_update(alpha + blank_t, emit_t)
        return new_alpha, new_alpha

    xs = (jnp.moveaxis(blank, 1, 0)[:-1], jnp.moveaxis(emit, 1, 0)[1:])
    _, rows = lax.scan(time_step, alpha, xs)
    all_alphas = jnp.concatenate([alpha[None], rows], axis=0)  # (T, B, U+1)

    # ll = alpha[f_len-1, y_len] + blank[f_len-1, y_len]
    t_idx = jnp.clip(f_len - 1, 0, t_max - 1)
    a_at = all_alphas[t_idx, jnp.arange(b), :]  # (B, U+1)
    a_fin = jnp.take_along_axis(a_at, y_len[:, None], axis=1)[:, 0]
    bl_at = blank[jnp.arange(b), t_idx, :]
    bl_fin = jnp.take_along_axis(bl_at, y_len[:, None], axis=1)[:, 0]
    return -(a_fin + bl_fin)


class TransducerLoss:
    """Module wrapper (reference: transducer.py:68 ``TransducerLoss``)."""

    def __init__(self, fuse_softmax_backward: bool = True,
                 packed_input: bool = False):
        if packed_input:
            raise NotImplementedError(
                "packed input is a CUDA memory optimization; use the dense "
                "(masked) layout on TPU"
            )
        # fuse_softmax_backward is implicit: autodiff of log_softmax
        # produces the fused form

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)

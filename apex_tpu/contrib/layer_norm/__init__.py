"""FastLayerNorm (reference: apex/contrib/layer_norm/layer_norm.py:40-55,
template-specialized one-pass kernels in apex/contrib/csrc/layer_norm/).

On TPU the "fast" and the standard fused layernorm are the same Pallas
kernel — there is no hidden-size template table to outgrow — so this
module re-exports the normalization stack under the contrib name for API
parity.  The reference's hidden-size restriction (supported sizes only)
does not apply.
"""

from apex_tpu.normalization import FusedLayerNorm as FastLayerNorm
from apex_tpu.ops.layer_norm import (
    fused_layer_norm_affine as fast_layer_norm,
)

__all__ = ["FastLayerNorm", "fast_layer_norm"]

"""Fused ResNet bottleneck block, plus the spatially-parallel variant.

Capability match of ``apex.contrib.bottleneck``
(reference: apex/contrib/bottleneck/bottleneck.py — ``Bottleneck``
:112-217 on cudnn-frontend fused kernels, ``SpatialBottleneck`` :386-520
with halo exchange over a communicator).  XLA fuses conv+BN+ReLU chains
natively, so ``Bottleneck`` is the plain math; ``SpatialBottleneck``
shards the image height across a mesh axis and exchanges 1-row halos
with ``ppermute`` before the 3x3 conv — the reference's
spatial-parallel-conv capability (an early form of context parallelism)
expressed as an XLA collective.

Layout: NHWC (TPU-native; the reference also prefers channels-last).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.sync_batchnorm import sync_batch_norm
from apex_tpu.utils.convnet import conv_nhwc as _conv, he_init as _he
from apex_tpu._compat import axis_size as _axis_size

__all__ = ["Bottleneck", "SpatialBottleneck", "halo_exchange"]


def _bn(x, scale, bias, eps=1e-5, axis_name=None):
    """Per-batch BN via the shared SyncBN math; with ``axis_name`` the
    stats are psum-ed over that mesh axis so an H-sharded block
    normalizes exactly like its dense counterpart."""
    out, _, _ = sync_batch_norm(
        x, scale, bias, None, None, training=True, eps=eps,
        axis_name=axis_name,
    )
    return out


class Bottleneck:
    """conv1x1-BN-ReLU → conv3x3-BN-ReLU → conv1x1-BN + residual, ReLU
    (reference: bottleneck.py:112-217; the cudnn-frontend fusion graph is
    XLA's automatic conv-epilogue fusion here)."""

    expansion = 4

    def __init__(self, in_channels: int, bottleneck_channels: int,
                 out_channels: int, stride: int = 1,
                 params_dtype: Any = jnp.float32):
        self.in_channels = in_channels
        self.bottleneck_channels = bottleneck_channels
        self.out_channels = out_channels
        self.stride = stride
        self.use_proj = stride != 1 or in_channels != out_channels
        self.params_dtype = params_dtype

    def init(self, key) -> dict:
        ks = jax.random.split(key, 4)
        c_in, c_mid, c_out = (
            self.in_channels, self.bottleneck_channels, self.out_channels
        )
        bn = lambda c: {"scale": jnp.ones((c,), self.params_dtype),
                        "bias": jnp.zeros((c,), self.params_dtype)}
        params = {
            "conv1": _he(ks[0], (1, 1, c_in, c_mid), self.params_dtype),
            "bn1": bn(c_mid),
            "conv2": _he(ks[1], (3, 3, c_mid, c_mid), self.params_dtype),
            "bn2": bn(c_mid),
            "conv3": _he(ks[2], (1, 1, c_mid, c_out), self.params_dtype),
            "bn3": bn(c_out),
        }
        if self.use_proj:
            params["conv_proj"] = _he(
                ks[3], (1, 1, c_in, c_out), self.params_dtype
            )
            params["bn_proj"] = bn(c_out)
        return params

    def _conv2(self, params, x):
        return _conv(x, params["conv2"], stride=self.stride)

    _bn_axis = None  # SpatialBottleneck reduces stats over its axis

    def apply(self, params: dict, x: jnp.ndarray) -> jnp.ndarray:
        ax = self._bn_axis
        h = jax.nn.relu(_bn(_conv(x, params["conv1"]), **params["bn1"],
                            axis_name=ax))
        h = jax.nn.relu(_bn(self._conv2(params, h), **params["bn2"],
                            axis_name=ax))
        h = _bn(_conv(h, params["conv3"]), **params["bn3"], axis_name=ax)
        if self.use_proj:
            x = _bn(_conv(x, params["conv_proj"], stride=self.stride),
                    **params["bn_proj"], axis_name=ax)
        return jax.nn.relu(h + x)


def halo_exchange(x: jnp.ndarray, axis_name: str, halo: int = 1) -> jnp.ndarray:
    """Concatenate ``halo`` rows from the spatial neighbours onto a
    height-sharded NHWC tensor (reference: SpatialBottleneck's peer halo
    buffers, bottleneck.py:218-385).  Edge ranks get zero rows, matching
    conv zero padding at the true image border."""
    world = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    down = [(i, (i + 1) % world) for i in range(world)]
    up = [(i, (i - 1) % world) for i in range(world)]
    top_halo = lax.ppermute(x[:, -halo:], axis_name, down)  # from rank-1
    bot_halo = lax.ppermute(x[:, :halo], axis_name, up)     # from rank+1
    zeros = jnp.zeros_like(top_halo)
    top_halo = jnp.where(rank == 0, zeros, top_halo)
    bot_halo = jnp.where(rank == world - 1, zeros, bot_halo)
    return jnp.concatenate([top_halo, x, bot_halo], axis=1)


class SpatialBottleneck(Bottleneck):
    """Bottleneck with the image height sharded over ``axis_name``
    (reference: bottleneck.py:386-520): the 3x3 conv sees one halo row
    from each neighbour; all other ops are pointwise in H.  Only
    stride=1 keeps the H-sharding aligned (the reference has the same
    restriction on its spatial group)."""

    def __init__(self, *args, axis_name: str = "cp", **kw):
        super().__init__(*args, **kw)
        if self.stride != 1:
            raise NotImplementedError(
                "SpatialBottleneck supports stride=1 (H-sharding must stay "
                "aligned across the spatial group)"
            )
        self.axis_name = axis_name

    @property
    def _bn_axis(self):
        return self.axis_name

    def _conv2(self, params, x):
        x = halo_exchange(x, self.axis_name, halo=1)
        return lax.conv_general_dilated(
            x, params["conv2"].astype(x.dtype),
            window_strides=(1, 1),
            padding=((0, 0), (1, 1)),  # H handled by halos, W zero-padded
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

"""Fused softmax cross-entropy with label smoothing.

Capability match of ``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``
(reference: apex/contrib/xentropy/softmax_xentropy.py:4-28, kernels in
apex/contrib/csrc/xentropy/).  The reference fuses softmax+CE and does
an in-place bprop to save memory; under XLA the fused fwd/bwd falls out
of one jitted expression (log-sum-exp never materializes the softmax),
and a custom vjp keeps the backward to the same softmax-minus-delta form
the kernel uses.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["softmax_cross_entropy_loss", "SoftmaxCrossEntropyLoss"]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy_loss(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    smoothing: float = 0.0,
    half_to_float: bool = False,
):
    """Per-example smoothed CE. ``logits`` (..., V), ``labels`` (...).

    loss = (1-s)·nll(target) + s·mean-over-vocab nll
    (reference kernel semantics: label_smoothing spreads s uniformly).
    """
    loss, _ = _fwd_math(logits, labels, smoothing, half_to_float)
    return loss


def _fwd_math(logits, labels, smoothing, half_to_float):
    x = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(x, axis=-1, keepdims=True))
    shifted = x - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0]
    target_logit = jnp.take_along_axis(x, labels[..., None], axis=-1)[..., 0]
    nll = lse - target_logit
    if smoothing > 0.0:
        mean_logit = jnp.mean(x, axis=-1)
        smooth_nll = lse - mean_logit
        loss = (1.0 - smoothing) * nll + smoothing * smooth_nll
    else:
        loss = nll
    if not half_to_float:
        loss = loss.astype(logits.dtype)
    return loss, (logits, labels)


def _fwd(logits, labels, smoothing, half_to_float):
    return _fwd_math(logits, labels, smoothing, half_to_float)


def _bwd(smoothing, half_to_float, res, g):
    logits, labels = res
    x = logits.astype(jnp.float32)
    p = jax.nn.softmax(x, axis=-1)
    v = x.shape[-1]
    onehot = jax.nn.one_hot(labels, v, dtype=jnp.float32)
    # d loss/d logits = softmax - (1-s)*onehot - s/V   (kernel bprop form)
    dx = p - (1.0 - smoothing) * onehot - smoothing / v
    dx = dx * g[..., None].astype(jnp.float32)
    return dx.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_fwd, _bwd)


class SoftmaxCrossEntropyLoss:
    """Module-style wrapper (reference: ``SoftmaxCrossEntropyLoss.apply``
    signature: logits, labels, smoothing, padding_idx, half_to_float)."""

    def __init__(self, smoothing: float = 0.0, padding_idx: int = 0,
                 half_to_float: bool = False):
        self.smoothing = smoothing
        self.padding_idx = padding_idx
        self.half_to_float = half_to_float

    def __call__(self, logits: jnp.ndarray, labels: jnp.ndarray):
        losses = softmax_cross_entropy_loss(
            logits, labels, self.smoothing, self.half_to_float
        )
        if self.padding_idx is not None:
            losses = jnp.where(labels == self.padding_idx,
                               jnp.zeros_like(losses), losses)
        return losses

"""FMHA: fused multi-head attention with a varlen (cu_seqlens) API.

Capability match of ``apex.contrib.fmha``
(reference: apex/contrib/fmha/fmha.py:33-80, sm80-only kernels for
seqlen ∈ {128,256,384,512} in apex/contrib/csrc/fmha/).  The TPU flash
attention kernel has no sequence-length table, so this wrapper only adds
the reference's packed-varlen calling convention: qkv packed as
(total_tokens, 3, heads, head_dim) plus ``cu_seqlens`` prefix offsets.

Varlen is realized the XLA-friendly way: segment-id masking inside one
padded batch (dynamic shapes would defeat jit), which is how TPU
production stacks express varlen attention.  The segment masking happens
*inside* the flash kernel (``ops/attention.py``), so unlike the
reference's seqlen<=512 window this path has no length limit and never
materialises the (s, s) score matrix.

Seqlen-specialized dispatch: the reference's whole reason for its
{128,256,384,512} per-seqlen kernels is that short sequences want a
different schedule.  This wrapper now gets the same specialization for
free — ``flash_attention(implementation=None)`` walks the measured
three-tier ladder (``docs/attention.md``): a packed batch in the
reference's own seqlen window runs the single-pass fmha-short kernel
(``ops/attention_short.py``), the 512 < s <= ~2048 band runs the
pipelined fmha-mid kernel (``ops/attention_mid.py`` — streamed
k-blocks, batch*head packing, causal block-skip), and longer batches
keep the online-softmax flash kernel.  Pass ``implementation="short"``
/ ``"mid"`` (or ``"pallas"``/``"xla"``) to force a path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention

__all__ = ["fmha", "FMHA"]


def fmha(
    qkv: jnp.ndarray,
    cu_seqlens: jnp.ndarray,
    max_seq_len: int,
    causal: bool = False,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """Packed-varlen attention (reference: ``FMHAFun.apply``).

    ``qkv``: (total_tokens, 3, heads, head_dim); ``cu_seqlens``: (B+1,)
    int32 prefix sums.  Returns (total_tokens, heads, head_dim).
    """
    total, three, heads, d = qkv.shape
    assert three == 3
    b = cu_seqlens.shape[0] - 1

    # scatter packed tokens into a (b, max_seq_len) padded batch
    tok = jnp.arange(total)
    seg = jnp.searchsorted(cu_seqlens[1:], tok, side="right")  # (total,)
    pos = tok - cu_seqlens[seg]
    batch_idx = seg * max_seq_len + pos
    padded = jnp.zeros((b * max_seq_len, 3, heads, d), qkv.dtype)
    padded = padded.at[batch_idx].set(qkv)
    padded = padded.reshape(b, max_seq_len, 3, heads, d)

    q, k, v = (
        jnp.moveaxis(padded[:, :, i], 2, 1) for i in range(3)
    )  # (b, heads, s, d)
    lengths = cu_seqlens[1:] - cu_seqlens[:-1]  # (b,)
    key_pos = jnp.arange(max_seq_len)
    valid = key_pos[None, :] < lengths[:, None]  # (b, s)
    # real tokens are segment 0; query/key padding get distinct sentinels
    # so padded positions never attend or get attended
    q_seg = jnp.where(valid, 0, -1).astype(jnp.int32)
    kv_seg = jnp.where(valid, 0, -2).astype(jnp.int32)
    out = flash_attention(
        q, k, v, causal=causal, q_segment_ids=q_seg, kv_segment_ids=kv_seg,
        implementation=implementation,
    )
    out = jnp.moveaxis(out, 1, 2).reshape(b * max_seq_len, heads, d)
    return out[batch_idx]


class FMHA:
    """Module wrapper (reference: apex/contrib/fmha/fmha.py ``FMHA``).

    ``implementation=None`` (default) keeps the measured dispatch
    ladder (short kernel at or below the short crossover, pipelined
    mid kernel through the mid crossover, flash above); ``"short"`` /
    ``"mid"`` / ``"pallas"`` / ``"xla"`` force a path.
    """

    def __init__(self, causal: bool = False,
                 implementation: Optional[str] = None):
        self.causal = causal
        self.implementation = implementation

    def __call__(self, qkv, cu_seqlens, max_s):
        return fmha(qkv, cu_seqlens, max_s, causal=self.causal,
                    implementation=self.implementation)

"""ASP — automatic structured (2:4) sparsity.

Capability match of ``apex.contrib.sparsity``
(reference: apex/contrib/sparsity/asp.py:21-217, mask calculators in
sparse_masklib.py:1-184).  The reference keeps mask buffers on every
eligible module and monkey-patches ``optimizer.step`` to re-apply them;
the TPU-native design is functional: masks are a pytree computed from
params, applied with a tree-map, and optimizer integration is a wrapper
that re-masks after each step — no in-place mutation, jit-fusable into
the train step.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["create_mask", "ASP"]


def _m4n2_1d(w2d: jnp.ndarray) -> jnp.ndarray:
    """Keep the 2 largest-|w| of every contiguous group of 4 along the
    last dim (reference: sparse_masklib.py ``mn_1d_best``/``m4n2_1d``)."""
    rows, cols = w2d.shape
    if cols % 4:
        raise ValueError(
            f"2:4 sparsity needs a multiple-of-4 inner dim, got {cols}"
        )
    g = jnp.abs(w2d).reshape(rows, cols // 4, 4)
    # rank within each group; keep the top 2
    order = jnp.argsort(g, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = ranks >= 2
    return mask.reshape(rows, cols)


_PATTERNS = {"m4n2_1d": _m4n2_1d}


def create_mask(w: jnp.ndarray, pattern: str = "m4n2_1d") -> jnp.ndarray:
    """Boolean keep-mask with the requested structured pattern
    (reference: sparse_masklib.create_mask)."""
    if pattern not in _PATTERNS:
        raise ValueError(f"unknown sparsity pattern {pattern!r}")
    shape = w.shape
    w2d = w.reshape(-1, shape[-1])
    return _PATTERNS[pattern](w2d).reshape(shape)


def _default_eligible(path: tuple, leaf: Any) -> bool:
    """The reference prunes Linear/Conv weights with both dims ≥ some
    minimum and divisible by 4 (asp.py ``eligible``); here: ≥2-D leaves
    whose last dim divides by 4 and whose name isn't bias/norm-like."""
    if getattr(leaf, "ndim", 0) < 2 or leaf.shape[-1] % 4:
        return False
    name = str(path[-1]).lower() if path else ""
    return not any(t in name for t in ("bias", "scale", "norm", "embed"))


class ASP:
    """Functional ASP (reference: apex/contrib/sparsity/asp.py ``ASP``).

    Usage::

        asp = ASP()                       # whitelist by predicate
        masks = asp.compute_sparse_masks(params)
        params = asp.apply_masks(params, masks)   # prune_trained_model
        step = asp.wrap_optimizer_step(opt.step, masks)  # re-mask updates
    """

    def __init__(
        self,
        mask_calculator: str = "m4n2_1d",
        eligible: Optional[Callable[[tuple, Any], bool]] = None,
    ):
        self.pattern = mask_calculator
        self.eligible = eligible or _default_eligible

    def compute_sparse_masks(self, params: Any) -> Any:
        """(reference: asp.py:155-211) — all-True masks for ineligible
        leaves so the mask pytree always matches the params."""

        def mask(path, leaf):
            if self.eligible(path, leaf):
                return create_mask(leaf, self.pattern)
            return jnp.ones(jnp.shape(leaf), bool)

        return jax.tree_util.tree_map_with_path(mask, params)

    def apply_masks(self, params: Any, masks: Any) -> Any:
        """(reference: prune_trained_model, asp.py:212-217)"""
        return jax.tree.map(
            lambda p, m: jnp.where(m, p, jnp.zeros_like(p)), params, masks
        )

    def wrap_optimizer_step(self, step_fn: Callable, masks: Any) -> Callable:
        """The functional analog of ``init_optimizer_for_pruning``'s step
        patch (reference: asp.py:127-153): run the wrapped step, then
        re-apply the masks to the returned params."""

        def wrapped(state, grads, params, *a, **kw):
            new_params, new_state = step_fn(state, grads, params, *a, **kw)
            return self.apply_masks(new_params, masks), new_state

        return wrapped

    @staticmethod
    def sparsity(masks: Any) -> float:
        """Fraction of zeroed weights across all masked leaves."""
        leaves = jax.tree.leaves(masks)
        zeros = sum(int(jnp.size(m)) - int(jnp.sum(m)) for m in leaves)
        total = sum(int(jnp.size(m)) for m in leaves)
        return zeros / max(total, 1)

"""ASP — automatic structured (2:4) sparsity.

Capability match of ``apex.contrib.sparsity``
(reference: apex/contrib/sparsity/asp.py:21-217, mask calculators in
sparse_masklib.py:1-184).  The reference keeps mask buffers on every
eligible module and monkey-patches ``optimizer.step`` to re-apply them;
the TPU-native design is functional: masks are a pytree computed from
params, applied with a tree-map, and optimizer integration is a wrapper
that re-masks after each step — no in-place mutation, jit-fusable into
the train step.

Pattern library (reference: sparse_masklib.py):

- ``m4n2_1d``    — best 2-of-4 along the last dim, chosen by magnitude
  over all C(4,2)=6 valid group patterns (``mn_1d_best``).
- ``m4n2_2d_best`` — exhaustive best over the 90 valid 4x4 block
  patterns that are 2:4 along BOTH rows and columns (``mn_2d_best``) —
  the transposed weight stays 2:4, the property the reference uses to
  accelerate DGRAD.
- ``m4n2_2d_greedy`` — the reference's greedy per-block selection
  (``mn_2d_greedy``), vectorised over blocks with a scan instead of the
  reference's per-block Python loops.

All calculators are pure jax and jittable; the pattern tables are tiny
static numpy constants built once at import/trace time.

TPU note: TPUs have no sparse-MXU analog of Ampere's SpMMA, so ASP here
buys memory (masked weights compress) and regularisation parity, not a
matmul speedup.  The mask math is identical; only the hardware payoff
differs.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "create_mask",
    "mn_1d_best",
    "mn_2d_best",
    "mn_2d_greedy",
    "ASP",
    "prune_trained_model",
]


# ------------------------------------------------------------- pattern tables
def _valid_1d_patterns(m: int, n: int) -> np.ndarray:
    """All m-length 0/1 vectors with exactly n ones
    (reference: sparse_masklib.compute_valid_1d_patterns — which
    enumerates m! permutations; C(m,n) combinations give the same table
    without the factorial blow-up at larger m)."""
    combos = list(itertools.combinations(range(m), n))
    pats = np.zeros((len(combos), m), np.float32)
    for i, keep in enumerate(combos):
        pats[i, list(keep)] = 1.0
    return pats


def _valid_2d_patterns(m: int, n: int) -> np.ndarray:
    """All m x m 0/1 blocks whose every row has exactly n ones and every
    column at most n (reference: compute_valid_2d_patterns — for m=4,n=2
    column sums are then exactly 2, giving 90 doubly-2:4 patterns)."""
    if m > 5:
        raise ValueError(
            f"2d pattern enumeration is C(m,n)^m and impractical for m={m}; "
            "use mn_2d_greedy for larger blocks")
    rows = _valid_1d_patterns(m, n)
    valid = []
    for combo in itertools.product(range(len(rows)), repeat=m):
        block = rows[list(combo)]
        if (block.sum(axis=0) <= n).all():
            valid.append(block)
    return np.stack(valid)  # (P, m, m)


_PATTERN_CACHE: dict = {}


def _patterns_1d(m: int, n: int) -> np.ndarray:
    key = ("1d", m, n)
    if key not in _PATTERN_CACHE:
        _PATTERN_CACHE[key] = _valid_1d_patterns(m, n)
    return _PATTERN_CACHE[key]


def _patterns_2d(m: int, n: int) -> np.ndarray:
    key = ("2d", m, n)
    if key not in _PATTERN_CACHE:
        _PATTERN_CACHE[key] = _valid_2d_patterns(m, n)
    return _PATTERN_CACHE[key]


# ---------------------------------------------------------- mask calculators
def mn_1d_best(w2d: jnp.ndarray, m: int = 4, n: int = 2) -> jnp.ndarray:
    """Best n-of-m keep-mask along the last dim by kept |w| magnitude
    (reference: sparse_masklib.mn_1d_best — argmax over the pattern
    score matrix |w| @ P^T, which for exact-n patterns IS the top-n
    choice, computed the MXU-friendly way)."""
    rows, cols = w2d.shape
    if cols % m:
        raise ValueError(f"{n}:{m} sparsity needs a multiple-of-{m} inner dim, got {cols}")
    pats = jnp.asarray(_patterns_1d(m, n))  # (P, m)
    g = jnp.abs(w2d.astype(jnp.float32)).reshape(-1, m)
    best = jnp.argmax(g @ pats.T, axis=-1)  # (rows*cols/m,)
    return pats[best].reshape(rows, cols).astype(bool)


def mn_2d_best(w2d: jnp.ndarray, m: int = 4, n: int = 2) -> jnp.ndarray:
    """Exhaustive best m x m block mask that keeps the weight n:m sparse
    along BOTH rows and columns (reference: sparse_masklib.mn_2d_best).
    Scores all valid block patterns at once with one einsum (MXU) and
    gathers the argmax pattern per block."""
    rows, cols = w2d.shape
    if rows % m or cols % m:
        raise ValueError(f"2d {n}:{m} sparsity needs multiple-of-{m} dims, got {w2d.shape}")
    pats = jnp.asarray(_patterns_2d(m, n))  # (P, m, m)
    blocks = jnp.abs(
        w2d.astype(jnp.float32)
        .reshape(rows // m, m, cols // m, m)
        .transpose(0, 2, 1, 3)
    )  # (R, C, m, m)
    scores = jnp.einsum("rcij,pij->rcp", blocks, pats)
    best = jnp.argmax(scores, axis=-1)  # (R, C)
    mask = pats[best]  # (R, C, m, m)
    return (
        mask.transpose(0, 2, 1, 3).reshape(rows, cols).astype(bool)
    )


def mn_2d_greedy(w2d: jnp.ndarray, m: int = 4, n: int = 2) -> jnp.ndarray:
    """Greedy per-block doubly-n:m mask (reference:
    sparse_masklib.mn_2d_greedy): walk each block's entries in
    descending |w| order, keeping an entry unless its row or column
    already holds n kept entries.  The reference loops per block on the
    host; here one ``lax.scan`` over the sorted positions runs every
    block in parallel (trailing blocks when dims don't divide by m are
    left dense, matching the reference's rowCount/colCount cropping).

    Like the reference greedy, this can keep FEWER than n entries in a
    row/column when the only remaining candidates sit in already-full
    lines (kept count per line is ≤ n, not always == n); use
    ``mn_2d_best`` when exact doubly-n:m structure is required."""
    rows, cols = w2d.shape
    R, C = rows // m, cols // m
    if R == 0 or C == 0:
        return jnp.ones((rows, cols), bool)
    crop = jnp.abs(
        w2d[: R * m, : C * m]
        .astype(jnp.float32)
        .reshape(R, m, C, m)
        .transpose(0, 2, 1, 3)
    ).reshape(R * C, m * m)
    order = jnp.argsort(-crop, axis=-1)  # descending positions, (B, m*m)

    def pick(carry, idx):
        keep, rcnt, ccnt = carry  # (B, m*m), (B, m), (B, m)
        r, c = idx // m, idx % m
        b = jnp.arange(keep.shape[0])
        ok = (rcnt[b, r] < n) & (ccnt[b, c] < n)
        keep = keep.at[b, idx].set(ok)
        rcnt = rcnt.at[b, r].add(ok.astype(rcnt.dtype))
        ccnt = ccnt.at[b, c].add(ok.astype(ccnt.dtype))
        return (keep, rcnt, ccnt), None

    B = R * C
    init = (
        jnp.zeros((B, m * m), bool),
        jnp.zeros((B, m), jnp.int32),
        jnp.zeros((B, m), jnp.int32),
    )
    (keep, _, _), _ = jax.lax.scan(pick, init, order.T)
    block_mask = keep.reshape(R, C, m, m).transpose(0, 2, 1, 3).reshape(R * m, C * m)
    mask = jnp.ones((rows, cols), bool)
    return mask.at[: R * m, : C * m].set(block_mask)


def _m4n2_1d(w2d):
    return mn_1d_best(w2d, 4, 2)


def _m4n2_2d_best(w2d):
    return mn_2d_best(w2d, 4, 2)


def _m4n2_2d_greedy(w2d):
    return mn_2d_greedy(w2d, 4, 2)


_PATTERNS = {
    "m4n2_1d": _m4n2_1d,
    "m4n2_2d_best": _m4n2_2d_best,
    "m4n2_2d_greedy": _m4n2_2d_greedy,
}


def create_mask(w: jnp.ndarray, pattern: str = "m4n2_1d") -> jnp.ndarray:
    """Boolean keep-mask with the requested structured pattern
    (reference: sparse_masklib.create_mask, which routes 1-4d tensors
    into the 2d calculators).  nd handling: 1-3d collapse leading dims
    onto rows; 4d assumes the JAX conv layout HWIO and prunes along the
    input-channel axis, the analog of the reference pruning its OIHW
    convs along C (sparse_masklib.py:169-183)."""
    if pattern not in _PATTERNS:
        raise ValueError(f"unknown sparsity pattern {pattern!r}")
    calc = _PATTERNS[pattern]
    shape = w.shape
    if w.ndim <= 3:
        w2d = w.reshape(-1, shape[-1])
        return calc(w2d).reshape(shape)
    if w.ndim == 4:  # HWIO conv kernel: prune along I (axis 2)
        h, kw, i, o = shape
        w2d = w.transpose(0, 1, 3, 2).reshape(h * kw * o, i)
        mask = calc(w2d)
        return mask.reshape(h, kw, o, i).transpose(0, 1, 3, 2)
    raise ValueError(f"cannot sparsify a {w.ndim}-d tensor")


def _default_eligible(path: tuple, leaf: Any) -> bool:
    """The reference prunes Linear/Conv weights with both dims ≥ some
    minimum and divisible by 4 (asp.py ``eligible``); here: ≥2-D leaves
    whose last dim divides by 4 and whose name isn't bias/norm-like."""
    if getattr(leaf, "ndim", 0) < 2:
        return False
    # the pruned axis must divide by 4: last dim for 1-3d, the
    # input-channel axis (HWIO axis 2) for 4d conv kernels — keep this
    # in lock-step with create_mask's nd routing
    pruned_dim = leaf.shape[2] if leaf.ndim == 4 else leaf.shape[-1]
    if leaf.ndim > 4 or pruned_dim % 4:
        return False
    name = str(path[-1]).lower() if path else ""
    return not any(t in name for t in ("bias", "scale", "norm", "embed"))


class ASP:
    """Functional ASP (reference: apex/contrib/sparsity/asp.py ``ASP``).

    Usage::

        asp = ASP()                       # whitelist by predicate
        masks = asp.compute_sparse_masks(params)
        params = asp.apply_masks(params, masks)   # prune_trained_model
        step = asp.wrap_optimizer_step(opt.step, masks)  # re-mask updates

    The reference's ``allow_recompute_mask`` (keep the pruned values so
    dense weights can be restored, asp.py:66-68,117-121) maps to
    ``extract_pruned`` / ``restore_dense``: because params are immutable
    here, the pruned residue is just another pytree.
    """

    def __init__(
        self,
        mask_calculator: str = "m4n2_1d",
        eligible: Optional[Callable[[tuple, Any], bool]] = None,
    ):
        self.pattern = mask_calculator
        self.eligible = eligible or _default_eligible

    def compute_sparse_masks(self, params: Any) -> Any:
        """(reference: asp.py:155-211) — all-True masks for ineligible
        leaves so the mask pytree always matches the params."""

        def mask(path, leaf):
            if self.eligible(path, leaf):
                return create_mask(leaf, self.pattern)
            return jnp.ones(jnp.shape(leaf), bool)

        return jax.tree_util.tree_map_with_path(mask, params)

    def apply_masks(self, params: Any, masks: Any) -> Any:
        """(reference: prune_trained_model, asp.py:212-217)"""
        return jax.tree.map(
            lambda p, m: jnp.where(m, p, jnp.zeros_like(p)), params, masks
        )

    def extract_pruned(self, params: Any, masks: Any) -> Any:
        """The values a mask removes (reference: allow_recompute_mask's
        ``__..._mma_pruned_p`` buffers, asp.py:117-121)."""
        return jax.tree.map(
            lambda p, m: jnp.where(m, jnp.zeros_like(p), p), params, masks
        )

    def restore_dense(self, params: Any, masks: Any, pruned: Any) -> Any:
        """Undo ``apply_masks`` given the extracted residue."""
        return jax.tree.map(
            lambda p, m, r: jnp.where(m, p, r), params, masks, pruned
        )

    def wrap_optimizer_step(self, step_fn: Callable, masks: Any) -> Callable:
        """The functional analog of ``init_optimizer_for_pruning``'s step
        patch (reference: asp.py:127-153): run the wrapped step, then
        re-apply the masks to the returned params."""

        def wrapped(state, grads, params, *a, **kw):
            new_params, new_state = step_fn(state, grads, params, *a, **kw)
            return self.apply_masks(new_params, masks), new_state

        return wrapped

    @staticmethod
    def sparsity(masks: Any) -> float:
        """Fraction of zeroed weights across all masked leaves."""
        leaves = jax.tree.leaves(masks)
        zeros = sum(int(jnp.size(m)) - int(jnp.sum(m)) for m in leaves)
        total = sum(int(jnp.size(m)) for m in leaves)
        return zeros / max(total, 1)


def prune_trained_model(
    params: Any,
    step_fn: Callable,
    mask_calculator: str = "m4n2_1d",
    eligible: Optional[Callable[[tuple, Any], bool]] = None,
) -> Tuple[Any, Any, Callable]:
    """One-call fine-tuning lifecycle (reference: asp.py:212-217
    ``prune_trained_model = init_model + init_optimizer +
    compute_sparse_masks``): returns the pruned params, the masks, and a
    mask-preserving optimizer step for the sparse fine-tune phase."""
    asp = ASP(mask_calculator, eligible)
    masks = asp.compute_sparse_masks(params)
    return asp.apply_masks(params, masks), masks, asp.wrap_optimizer_step(step_fn, masks)

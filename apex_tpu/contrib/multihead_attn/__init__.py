"""Fused multi-head attention modules.

Capability match of ``apex.contrib.multihead_attn``
(reference: apex/contrib/multihead_attn/self_multihead_attn.py:26-124,
encdec_multihead_attn.py, ~9.5k LoC of CUDA variants): self- and
encoder-decoder MHA with optional fused layernorm+residual-add
(``include_norm_add``), optional biases, additive masks, and two
implementations — ``impl='fast'`` (Pallas flash attention) and
``impl='default'`` (plain XLA reference math), mirroring the reference's
fast-kernel vs pure-PyTorch pair used to cross-check each other.

Layout convention matches the reference: inputs are
(seq, batch, hidden) ("SBH", the torch MHA convention).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention, mha_reference
from apex_tpu.ops.layer_norm import fused_layer_norm_affine

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]


def _xavier(key, shape, dtype, gain=1.0):
    fan_in, fan_out = shape[0], shape[-1]
    a = gain * math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -a, a)


def _attend(q, k, v, scale, mask_bias, causal, impl,
            kv_pad_mask=None, dropout_rate=0.0, rng=None,
            attention_impl=None):
    """q,k,v: (b, h, s, d).  mask_bias: additive (b,1,sq,sk) or None;
    kv_pad_mask: (b, sk) True = masked-out key (torch convention).

    Probability dropout happens *inside* the attention (the reference
    fuses it into its CUDA kernels via Philox; here the flash kernel's
    counter-based hash plays that role, and the 'default' XLA path draws
    the identical mask).  ``attention_impl`` is forwarded to
    ``flash_attention`` on the 'fast' path (None = the measured
    three-tier dispatch ladder: short sequences — the reference MHA
    extensions' own seqlen regime — run the single-pass fmha-short
    kernel, the 512 < s <= ~2048 band runs the pipelined fmha-mid
    kernel, longer sequences the streamed flash kernel;
    "short"/"mid"/"pallas"/"xla" force one — docs/attention.md)."""
    q_seg = kv_seg = None
    if kv_pad_mask is not None:
        # segment ids keep padding exclusion inside the flash kernel
        kv_seg = jnp.where(kv_pad_mask, -2, 0).astype(jnp.int32)
        q_seg = jnp.zeros((q.shape[0], q.shape[2]), jnp.int32)
    seed = None
    if dropout_rate > 0.0 and rng is not None:
        seed = jax.random.bits(rng, dtype=jnp.uint32)
    else:
        dropout_rate = 0.0
    kwargs = dict(
        causal=causal, sm_scale=scale, bias=mask_bias,
        q_segment_ids=q_seg, kv_segment_ids=kv_seg,
        dropout_rate=dropout_rate, dropout_seed=seed,
    )
    if impl == "fast":
        # attn_mask is a constant mask, never a parameter: skip dbias
        return flash_attention(q, k, v, bias_requires_grad=False,
                               implementation=attention_impl, **kwargs)
    return mha_reference(q, k, v, **kwargs)


class _MHABase:
    def __init__(
        self,
        embed_dim: int,
        num_heads: int,
        dropout: float = 0.0,
        bias: bool = False,
        include_norm_add: bool = False,
        impl: str = "fast",
        params_dtype: Any = jnp.float32,
        policy: Any = None,
        attention_impl: Any = None,
    ):
        norm_dtype = params_dtype
        if policy is not None:  # amp.Policy drives the param dtypes
            params_dtype = policy.param_dtype
            norm_dtype = (
                jnp.float32 if policy.keep_norm_fp32 else policy.param_dtype
            )
        if embed_dim % num_heads:
            raise ValueError("embed_dim must be divisible by num_heads")
        if impl not in ("fast", "default"):
            raise ValueError(f"unsupported impl: {impl!r}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.scale = self.head_dim**-0.5
        self.dropout = dropout
        self.use_bias = bias
        self.include_norm_add = include_norm_add
        self.impl = impl
        # kernel choice for impl='fast': None = the measured dispatch
        # ladder (short kernel in the reference extensions' seqlen
        # regime, pipelined mid kernel through ~2048, flash above),
        # "short"/"mid"/"pallas"/"xla" force one
        self.attention_impl = attention_impl
        self.params_dtype = params_dtype
        self.norm_dtype = norm_dtype

    def _ln_params(self):
        return {
            "scale": jnp.ones((self.embed_dim,), self.norm_dtype),
            "bias": jnp.zeros((self.embed_dim,), self.norm_dtype),
        }

    def _maybe_norm(self, params, x):
        if self.include_norm_add:
            return fused_layer_norm_affine(
                x, params["lyr_nrm"]["scale"], params["lyr_nrm"]["bias"],
                (self.embed_dim,),
            )
        return x

    def _sbh_to_bhsd(self, x):
        s, b, _ = x.shape
        x = x.reshape(s, b, self.num_heads, self.head_dim)
        return jnp.transpose(x, (1, 2, 0, 3))

    def _bhsd_to_sbh(self, x):
        b, h, s, d = x.shape
        return jnp.transpose(x, (2, 0, 1, 3)).reshape(s, b, h * d)


class SelfMultiheadAttn(_MHABase):
    """Self-attention (reference: self_multihead_attn.py:26-124).

    ``apply(params, query, key_padding_mask=None, attn_mask=None,
    is_training=True, rng=None)`` → (seq, batch, hidden); with
    ``include_norm_add`` the residual add of the *input* is fused in,
    exactly like the reference's norm-add variants.
    """

    def init(self, key) -> Dict[str, Any]:
        k1, k2 = jax.random.split(key)
        params = {
            # packed qkv, output dim grouped per head (q,k,v triplets)
            "qkv_weight": _xavier(
                k1, (self.embed_dim, 3 * self.embed_dim), self.params_dtype
            ),
            "out_weight": _xavier(
                k2, (self.embed_dim, self.embed_dim), self.params_dtype
            ),
        }
        if self.use_bias:
            params["qkv_bias"] = jnp.zeros(
                (3 * self.embed_dim,), self.params_dtype
            )
            params["out_bias"] = jnp.zeros(
                (self.embed_dim,), self.params_dtype
            )
        if self.include_norm_add:
            params["lyr_nrm"] = self._ln_params()
        return params

    def apply(
        self,
        params: Dict[str, Any],
        query: jnp.ndarray,
        key_padding_mask: Optional[jnp.ndarray] = None,
        attn_mask: Optional[jnp.ndarray] = None,
        causal: bool = False,
        is_training: bool = True,
        rng: Optional[jax.Array] = None,
    ) -> jnp.ndarray:
        s, b, _ = query.shape
        x = self._maybe_norm(params, query)
        qkv = jnp.matmul(x, params["qkv_weight"].astype(x.dtype))
        if self.use_bias:
            qkv = qkv + params["qkv_bias"].astype(qkv.dtype)
        qkv = qkv.reshape(s, b, self.num_heads, 3, self.head_dim)
        q, k, v = (
            jnp.transpose(qkv[:, :, :, i], (1, 2, 0, 3)) for i in range(3)
        )

        bias = None
        if attn_mask is not None:
            add = jnp.where(attn_mask, -1e30, 0.0) if attn_mask.dtype == jnp.bool_ \
                else attn_mask
            add = jnp.broadcast_to(add, (b, 1, s, s)) if add.ndim == 2 \
                else add
            bias = add

        ctx = _attend(
            q, k, v, self.scale, bias, causal, self.impl,
            kv_pad_mask=key_padding_mask,
            dropout_rate=self.dropout if is_training else 0.0, rng=rng,
            attention_impl=self.attention_impl,
        )
        out = jnp.matmul(
            self._bhsd_to_sbh(ctx), params["out_weight"].astype(ctx.dtype)
        )
        if self.use_bias:
            out = out + params["out_bias"].astype(out.dtype)
        if self.include_norm_add:
            out = out + query  # fused residual add (norm-add variant)
        return out


class EncdecMultiheadAttn(_MHABase):
    """Encoder-decoder attention (reference: encdec_multihead_attn.py):
    Q from the decoder stream, K/V projected together from the encoder
    stream."""

    def init(self, key) -> Dict[str, Any]:
        k1, k2, k3 = jax.random.split(key, 3)
        params = {
            "q_weight": _xavier(
                k1, (self.embed_dim, self.embed_dim), self.params_dtype
            ),
            "kv_weight": _xavier(
                k2, (self.embed_dim, 2 * self.embed_dim), self.params_dtype
            ),
            "out_weight": _xavier(
                k3, (self.embed_dim, self.embed_dim), self.params_dtype
            ),
        }
        if self.use_bias:
            params["q_bias"] = jnp.zeros((self.embed_dim,), self.params_dtype)
            params["kv_bias"] = jnp.zeros(
                (2 * self.embed_dim,), self.params_dtype
            )
            params["out_bias"] = jnp.zeros(
                (self.embed_dim,), self.params_dtype
            )
        if self.include_norm_add:
            params["lyr_nrm"] = self._ln_params()
        return params

    def apply(
        self,
        params: Dict[str, Any],
        query: jnp.ndarray,
        key: jnp.ndarray,
        key_padding_mask: Optional[jnp.ndarray] = None,
        is_training: bool = True,
        rng: Optional[jax.Array] = None,
    ) -> jnp.ndarray:
        sq, b, _ = query.shape
        x = self._maybe_norm(params, query)
        q = jnp.matmul(x, params["q_weight"].astype(x.dtype))
        if self.use_bias:
            q = q + params["q_bias"].astype(q.dtype)
        kv = jnp.matmul(key, params["kv_weight"].astype(key.dtype))
        if self.use_bias:
            kv = kv + params["kv_bias"].astype(kv.dtype)
        sk = key.shape[0]
        kv = kv.reshape(sk, b, self.num_heads, 2, self.head_dim)
        k_, v_ = (
            jnp.transpose(kv[:, :, :, i], (1, 2, 0, 3)) for i in range(2)
        )
        q = self._sbh_to_bhsd(q)

        ctx = _attend(
            q, k_, v_, self.scale, None, False, self.impl,
            kv_pad_mask=key_padding_mask,
            dropout_rate=self.dropout if is_training else 0.0, rng=rng,
            attention_impl=self.attention_impl,
        )
        out = jnp.matmul(
            self._bhsd_to_sbh(ctx), params["out_weight"].astype(ctx.dtype)
        )
        if self.use_bias:
            out = out + params["out_bias"].astype(out.dtype)
        if self.include_norm_add:
            out = out + query
        return out

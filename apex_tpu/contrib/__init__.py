"""Contrib tier: the TPU-native equivalents of ``apex.contrib``."""

"""Group BatchNorm, NHWC, with fused add+ReLU.

Capability match of ``apex.contrib.groupbn``
(reference: apex/contrib/groupbn/batch_norm.py:116-234
``BatchNorm2d_NHWC``, raw-IPC peer buffers in apex/contrib/csrc/groupbn/).
NHWC is the native TPU layout, and the "BN group" peer-to-peer stats
exchange maps to a group-limited psum over the dp axis — the machinery
already in :func:`apex_tpu.parallel.sync_batch_norm` (its
``process_group_size`` argument is exactly ``bn_group``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import sync_batch_norm
from apex_tpu.transformer.parallel_state import DATA_PARALLEL_AXIS

__all__ = ["BatchNorm2d_NHWC", "batch_norm_nhwc"]


def batch_norm_nhwc(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray,
    running_mean: Optional[jnp.ndarray] = None,
    running_var: Optional[jnp.ndarray] = None,
    *,
    z: Optional[jnp.ndarray] = None,
    training: bool = True,
    momentum: float = 0.1,
    eps: float = 1e-5,
    bn_group: int = 1,
    axis_name: Optional[str] = DATA_PARALLEL_AXIS,
    fuse_relu: bool = False,
):
    """NHWC batchnorm with optional fused residual-add (+ReLU)
    (reference: ``batch_norm_add_relu``).  ``z`` is the residual."""
    out, rm, rv = sync_batch_norm(
        x, weight, bias, running_mean, running_var,
        training=training, momentum=momentum, eps=eps,
        axis_name=axis_name if bn_group != 1 else None,
        process_group_size=0 if bn_group in (0, 1) else bn_group,
        fuse_relu=False,
    )
    if z is not None:
        out = out + z.astype(out.dtype)
    if fuse_relu:
        out = jax.nn.relu(out)
    return out, rm, rv


class BatchNorm2d_NHWC:
    """Module form (reference: batch_norm.py:116-234): channels-last BN
    whose stats are shared among groups of ``bn_group`` dp ranks."""

    def __init__(self, num_features: int, fuse_relu: bool = False,
                 bn_group: int = 1, momentum: float = 0.1, eps: float = 1e-5,
                 params_dtype: Any = jnp.float32,
                 axis_name: str = DATA_PARALLEL_AXIS):
        self.num_features = num_features
        self.fuse_relu = fuse_relu
        self.bn_group = bn_group
        self.momentum = momentum
        self.eps = eps
        self.params_dtype = params_dtype
        self.axis_name = axis_name

    def init(self, key=None) -> dict:
        f = self.num_features
        return {
            "weight": jnp.ones((f,), self.params_dtype),
            "bias": jnp.zeros((f,), self.params_dtype),
            "running_mean": jnp.zeros((f,), jnp.float32),
            "running_var": jnp.ones((f,), jnp.float32),
        }

    def apply(self, params: dict, x: jnp.ndarray,
              z: Optional[jnp.ndarray] = None, training: bool = True):
        """Returns (out, new_params) — running stats are values, not
        buffers, in the functional style."""
        out, rm, rv = batch_norm_nhwc(
            x, params["weight"], params["bias"],
            params["running_mean"], params["running_var"],
            z=z, training=training, momentum=self.momentum, eps=self.eps,
            bn_group=self.bn_group, axis_name=self.axis_name,
            fuse_relu=self.fuse_relu,
        )
        new_params = dict(params, running_mean=rm, running_var=rv)
        return out, new_params

"""ZeRO-style distributed optimizers: state sharded over the dp axis.

Capability match of the reference's ``DistributedFusedAdam`` /
``DistributedFusedLAMB``
(reference: apex/contrib/optimizers/distributed_fused_adam.py:9-636,
distributed_fused_lamb.py:10-910): gradients are **reduce-scattered**
across data-parallel ranks, each rank runs the optimizer step on its own
1/dp shard of a flat fp32 buffer (moments and fp32 masters live only for
that shard), and the updated parameters are **all-gathered** back.

TPU-native redesign: the reference's flat-buffer block/chunk machinery,
multiple process-group pools (``dwu_num_rs_pg/ar_pg/ag_pg``) and manual
stream pipelining exist to overlap NCCL with CUDA compute; under XLA the
collectives (``psum_scatter`` / ``all_gather`` over the "dp" mesh axis)
are scheduled and overlapped by the compiler, and the two-level
intra/inter-group hierarchy maps onto nested mesh axes (ICI inside a
pod, DCN across pods) without optimizer involvement.  What remains is
the math — ~150 lines instead of ~4k.

LAMB's per-parameter trust ratios survive flat sharding via segment
reductions: each flat element carries its parameter id, per-parameter
partial norms are ``segment_sum``-ed locally and ``psum``-ed across the
shard boundary, so the trust ratio is bitwise the same as the unsharded
optimizer.

Call :meth:`init` and :meth:`step` inside ``shard_map``; state specs come
from :meth:`state_specs`.

**Full-parameter sharding (ZeRO-3/FSDP)** — ``shard_params=True``:
parameters themselves live permanently as the 1-D fp32 shard in the
bucket-shaped flat layout (:class:`apex_tpu.parallel.zero3.Zero3Layout`
over the PR 4 ``GradientBuckets`` plans), :meth:`gather_params`
rebuilds the model-dtype tree per bucket ON USE (int8 + ``ag`` error
feedback under ``CompressionConfig(ici_legs=True)``), gradients
reduce-scatter straight into the shard and the update runs there in
place — no replicated master, no tail all-gather, persistent
per-device bytes down ~world-fold (the h≥4096 unlock,
PROFILE_r05.md).  Entry points: :meth:`build_layout` (host-side,
once), :meth:`init_shards`, :meth:`gather_params`, :meth:`step` (same
method, shard-aware), :meth:`unshard_params` (checkpoint → replicated
eval).  At ``compression=None`` the step is bit-identical to the
state-sharding mode — a storage layout, not a numerics change.  See
docs/distributed.md "Full-parameter sharding".

MoE composition: pass ``param_specs=`` to :class:`DistributedFusedAdam`
and leaves whose spec names the data axis (expert weights riding "dp"
as ep) are updated rank-locally with fp32 masters instead of riding the
flat buffer — see the class docs and docs/optimizers.md.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers.base import f32, tree_where
from apex_tpu.transformer.parallel_state import DATA_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    all_gather_invariant,
)

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB",
           "reestablish_replicated"]


def _axis_size(axis_name) -> int:
    """Static axis extent (shared version-portable shim)."""
    from apex_tpu._compat import axis_size

    return int(axis_size(axis_name))


def reestablish_replicated(params: Any, param_specs: Any,
                           axes: Tuple[str, ...] = ("pp", "tp")) -> Any:
    """Re-mark model-axis-replicated params invariant after a ZeRO step.

    Composing the sharded optimizer with pipeline/tensor parallelism
    flattens replicated leaves (embeddings, norms) into the same flat
    buffer as pp/tp-sharded layers, so the all-gathered params come back
    typed varying over those axes even though replicated leaves carry
    identical values on every rank (their grads were synced before the
    step).  A pmean over the missing axes is a numeric no-op that
    restores the invariant type so ``out_specs`` like ``P()`` typecheck.
    Call inside shard_map on the params returned by :meth:`step`."""
    from apex_tpu.transformer.parallel_state import spec_axis_names

    def fix(p, s):
        names = spec_axis_names(s)
        for ax in axes:
            try:
                varying = ax in jax.typeof(p).vma
            except Exception:
                varying = True
            if ax not in names and varying:
                p = lax.pmean(p, ax)
        return p

    return jax.tree.map(fix, params, param_specs,
                        is_leaf=lambda x: isinstance(x, P))


class _FlatMeta:
    """Host-side flattening metadata for a param pytree."""

    def __init__(self, params: Any, world: int):
        leaves = jax.tree.leaves(params)
        self.treedef = jax.tree.structure(params)
        self.shapes = [jnp.shape(l) for l in leaves]
        self.dtypes = [jnp.asarray(l).dtype for l in leaves]
        self.sizes = [int(jnp.size(l)) for l in leaves]
        self.total = sum(self.sizes)
        self.padded = -(-self.total // world) * world
        self.shard = self.padded // world
        self.num_leaves = len(leaves)

    def flatten(self, tree: Any) -> jnp.ndarray:
        leaves = jax.tree.leaves(tree)
        flat = jnp.concatenate(
            [jnp.ravel(l).astype(jnp.float32) for l in leaves]
        )
        return jnp.pad(flat, (0, self.padded - self.total))

    def unflatten(self, flat: jnp.ndarray) -> Any:
        out, off = [], 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            out.append(flat[off : off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree.unflatten(self.treedef, out)

    def segment_ids(self) -> jnp.ndarray:
        """Flat-index → leaf-id map; padding gets the extra id
        ``num_leaves`` so it never contaminates a real parameter."""
        ids = jnp.concatenate(
            [
                jnp.full((s,), i, jnp.int32)
                for i, s in enumerate(self.sizes)
            ]
        )
        return jnp.pad(
            ids, (0, self.padded - self.total),
            constant_values=self.num_leaves,
        )


class _DistributedOptimizer:
    """Shared reduce-scatter → sharded step → all-gather skeleton.

    ``axis_name`` may be a single mesh axis ("dp") or a **nested pair**
    ``(dcn_axis, ici_axis)`` for the reference's two-level hierarchy
    (reference: distributed_fused_adam.py:106-160, intra-group
    reduce-scatter + inter-group all-reduce with dwu_group_size): grads
    reduce-scatter *within* the fast ici axis, the resulting 1/ici
    shards all-reduce *across* the slow dcn axis (each DCN message is
    1/ici of the gradient), the sharded step runs per ici rank with
    state replicated across dcn groups, and the all-gather rides ici
    only — no parameter bytes ever cross DCN.
    """

    def __init__(self, lr: float, axis_name: Any = DATA_PARALLEL_AXIS,
                 compressed_allgather: Optional[str] = None,
                 param_specs: Any = None,
                 compression: Any = None,
                 shard_params: bool = False,
                 bucket_bytes: Optional[int] = None):
        from apex_tpu.ops.quantization import as_compression_config
        from apex_tpu.parallel.overlap import DEFAULT_BUCKET_BYTES

        if compressed_allgather not in (None, "bf16", "e5m2"):
            raise ValueError(
                "compressed_allgather must be None, 'bf16' or 'e5m2'"
            )
        # ZeRO-3 / FSDP: parameters live permanently as 1-D fp32 shards
        # in the bucket-shaped flat layout (apex_tpu/parallel/zero3.py)
        # and are all-gathered to model dtype per bucket ON USE
        # (:meth:`gather_params`); gradients reduce-scatter straight
        # into the shard and the update runs on it in place — no
        # replicated master, no tail all-gather.  Requires
        # :meth:`build_layout` once, host-side, before any use.
        self.shard_params = bool(shard_params)
        self.bucket_bytes = (DEFAULT_BUCKET_BYTES if bucket_bytes is None
                             else int(bucket_bytes))
        if self.bucket_bytes < 1:
            raise ValueError("bucket_bytes must be >= 1")
        self._layout = None
        if shard_params and compressed_allgather is not None:
            raise ValueError(
                "shard_params gathers weights in MODEL dtype already "
                "(bf16 params move bf16 bytes) and compresses the "
                "gather to int8 under CompressionConfig(ici_legs=True) "
                "— compressed_allgather does not apply; drop it"
            )
        self.lr = lr
        self.axis_name = axis_name
        # opt-in int8 quantization of the DCN leg of the hierarchical
        # gradient reduce (the lax.psum of the 1/ici reduce-scattered
        # shard across dcn) — by default the ici RS leg, the fp32
        # masters and the param all-gather are untouched; with
        # CompressionConfig(ici_legs=True) the grad RS over ici also
        # goes int8 (the param gather stays governed by
        # compressed_allgather).  Error feedback (config default)
        # rides the optimizer state as state["comm"]
        self.compression = as_compression_config(compression)
        if self.compression is not None and not isinstance(
            axis_name, (tuple, list)
        ):
            raise ValueError(
                "compression quantizes the DCN leg of the hierarchical "
                "reduce: pass axis_name=(dcn_axis, ici_axis)"
            )
        # opt-in lossy compression of the parameter all-gather payload
        # (reference: distributed_fused_adam.py e5m2 compressed allgather):
        # masters stay fp32; only the gathered bytes shrink 2x/4x
        self.compressed_allgather = compressed_allgather
        # param_specs enables DATA-AXIS-SHARDED leaves (MoE expert
        # weights riding "dp" as the ep axis): those leaves must NOT go
        # through the flat reduce-scatter/all-gather (each rank owns
        # its experts outright — an RS over dp would sum unrelated
        # shards); they get a rank-LOCAL fp32-master update instead,
        # selected by whether the leaf's spec names the shard axis
        self.param_specs = param_specs
        # cached at construction: pure function of (param_specs, axes)
        self._mask = (self._local_mask()
                      if param_specs is not None else None)
        if self._mask is not None and self._has_local(self._mask):
            # fail FAST, not at step-trace time
            if self.shard_params:
                raise NotImplementedError(
                    "shard_params (ZeRO-3) does not support data-axis-"
                    "sharded leaves: an expert shard has no replicated "
                    "copy to re-shard, and the rank-local path performs "
                    "no gather — drop param_specs' data-axis entries or "
                    "use the state-sharding mode for MoE"
                )
            if self._hierarchical:
                raise NotImplementedError(
                    "data-axis-sharded leaves are not supported with "
                    "a hierarchical axis_name: the rank-local path "
                    "performs no collectives, so the cross-axis "
                    "(dcn) replicas would silently diverge"
                )
            if (type(self)._local_update
                    is _DistributedOptimizer._local_update):
                raise NotImplementedError(
                    f"{type(self).__name__} does not support "
                    "data-axis-sharded params (its update couples "
                    "leaves globally, e.g. the LAMB grad-norm "
                    "clip); use DistributedFusedAdam for MoE "
                    "expert-parallel models or drop param_specs"
                )
        else:
            self._mask = None  # no local leaves: one uniform flat path

    # ---------------------------------------------------- local leaves
    def _local_mask(self):
        """Pytree of bools over param_specs: True = leaf storage is
        sharded over the data axis → rank-local update path."""
        from apex_tpu.transformer.parallel_state import spec_axis_names

        axes = {self._shard_axis}
        if self._cross_axis is not None:
            axes.add(self._cross_axis)
        return jax.tree.map(
            lambda s: bool(axes & set(spec_axis_names(s))),
            self.param_specs, is_leaf=lambda x: isinstance(x, P),
        )

    @staticmethod
    def _mask_tree(tree: Any, mask: Any, keep_local: bool) -> Any:
        """Replace the unwanted half's leaves with 0-size placeholders
        (structure stays identical, flatten skips zero elements)."""
        def f(m, x):
            if m == keep_local:
                return x
            return jnp.zeros((0,), jnp.asarray(x).dtype)

        return jax.tree.map(f, mask, tree)

    def _has_local(self, mask) -> bool:
        return any(jax.tree.leaves(mask))

    def _local_update(self, extra: dict, step, g, p, lr):
        """Per-leaf update rule for data-axis-sharded leaves; only
        optimizers without cross-leaf coupling can support it."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support data-axis-sharded "
            "params (its update couples leaves globally, e.g. the LAMB "
            "grad-norm clip); use DistributedFusedAdam for MoE "
            "expert-parallel models or drop param_specs"
        )

    @property
    def _hierarchical(self) -> bool:
        return isinstance(self.axis_name, (tuple, list))

    @property
    def _shard_axis(self) -> str:
        """Axis the state shards over (ici for hierarchical)."""
        return self.axis_name[1] if self._hierarchical else self.axis_name

    @property
    def _cross_axis(self) -> Optional[str]:
        """Axis the reduced shards all-reduce across (dcn), if any."""
        return self.axis_name[0] if self._hierarchical else None

    # subclass hook: update on the local 1-D fp32 shard
    def _update_shard(
        self, extra: dict, step, g, p, lr, meta: _FlatMeta, ids_local
    ) -> Tuple[jnp.ndarray, dict]:
        raise NotImplementedError

    def _extra_init(self, shard_size: int) -> dict:
        return {
            "exp_avg": jnp.zeros((shard_size,), jnp.float32),
            "exp_avg_sq": jnp.zeros((shard_size,), jnp.float32),
        }

    def state_specs(self, model_axes: Tuple[str, ...] = ()) -> dict:
        """shard_map specs for the sharded state.

        ``model_axes``: mesh axes the *params* are sharded over (e.g.
        ``("pp", "tp")`` when composing ZeRO with pipeline/tensor
        parallelism).  Each (pp, tp) position runs its own independent
        dp-sharded flat buffer over its local params, so the state is
        varying over those axes too — the spec must say so or
        shard_map's varying-mesh-axes check rejects the program."""
        ax = ((*model_axes, self._shard_axis) if model_axes
              else self._shard_axis)
        specs = {k: P(ax) for k in self._extra_init(1)}
        specs["step"] = P()
        if self.shard_params:
            # ZeRO-3: no master (the threaded shard is the master);
            # per-BUCKET residuals — grad legs vary over both data
            # axes, the param-AG residual rides ici only (it
            # compensates the dcn-invariant shard)
            if (self.compression is not None
                    and self.compression.error_feedback):
                from apex_tpu.parallel.zero3 import zero3_comm_specs

                specs["comm"] = zero3_comm_specs(
                    self.layout, self.axis_name, self.compression,
                    model_axes=model_axes,
                )
            return specs
        specs["master"] = P(ax)
        if (self.compression is not None
                and self.compression.error_feedback):
            # quantization residuals vary over BOTH data axes: each
            # (dcn, ici) position compensates its own rounding error.
            # ici_legs adds the RS leg's residual (the grad all-gather
            # has no analog here — ZeRO gathers PARAMS, covered by
            # compressed_allgather)
            cax = ((*model_axes, self._cross_axis, self._shard_axis)
                   if model_axes
                   else (self._cross_axis, self._shard_axis))
            keys = ["push", "pull"]
            if self.compression.ici_legs:
                keys.append("ici_push")
            specs["comm"] = {k: P(cax) for k in keys}
        if self._mask is not None:
            # data-axis-sharded leaves keep the PARAM's own spec: their
            # state lives exactly where the shard lives.  NOTE the spec
            # must fully describe the leaf's model-axis sharding too
            # (true for the models here: pipeline expert stacks are
            # P("pp", ..., "dp", ...)); the replicated half's 0-size
            # placeholders are P()
            lspec = jax.tree.map(
                lambda m, s: s if m else P(),
                self._mask, self.param_specs,
            )
            moment_keys = list(self._extra_init(1))
            specs["local"] = {"master": lspec,
                              **{k: lspec for k in moment_keys}}
        return specs

    # ------------------------------------------------ ZeRO-3 (FSDP)
    def build_layout(self, params_like: Any, mesh=None,
                     world: Optional[int] = None):
        """Build (and remember) the host-side ZeRO-3 shard layout for a
        param pytree — REQUIRED once before any ``shard_params`` use.
        ``params_like`` may be arrays or ``ShapeDtypeStruct``\\ s; pass
        ``mesh`` so the shard-axis extent (and, with ``param_specs``,
        per-device leaf shapes for pp/tp-sharded models) are derived,
        or give ``world`` explicitly.  Returns the
        :class:`~apex_tpu.parallel.zero3.Zero3Layout`."""
        from apex_tpu.parallel.zero3 import Zero3Layout

        if not self.shard_params:
            raise ValueError(
                "build_layout is the ZeRO-3 entry: construct the "
                "optimizer with shard_params=True"
            )
        if world is None:
            if mesh is None:
                raise ValueError("build_layout needs mesh= or world=")
            world = mesh.shape[self._shard_axis]
        self._layout = Zero3Layout(
            params_like, world, self.bucket_bytes,
            param_specs=self.param_specs, mesh=mesh,
        )
        return self._layout

    @property
    def layout(self):
        if self._layout is None:
            raise ValueError(
                "no ZeRO-3 layout built: call build_layout(params, "
                "mesh=...) once, host-side, before init_shards/"
                "gather_params/step"
            )
        return self._layout

    def shard_spec(self, model_axes: Tuple[str, ...] = ()):
        """Placement spec for the flat param shard (1/ici per device,
        replicated across dcn; varying over ``model_axes`` when
        composing with pp/tp — each position holds its own local
        stack's shard)."""
        ax = ((*model_axes, self._shard_axis) if model_axes
              else self._shard_axis)
        return P(ax)

    def init_shards(self, params: Any) -> jnp.ndarray:
        """Replicated params → this rank's permanent ``(shard_size,)``
        fp32 shard (call inside shard_map; the shard IS the fp32
        master from here on — the replicated tree can be dropped)."""
        rank = lax.axis_index(self._shard_axis)
        return self.layout.shard_params(params, rank)

    def gather_params(
        self, shards: jnp.ndarray, state: Optional[dict] = None,
    ) -> Tuple[Any, Optional[dict]]:
        """Gather-on-use: the full model-dtype param pytree from the
        flat shard, one all-gather per bucket over the shard (ici)
        axis — int8 + error feedback when the compression config says
        ``ici_legs`` (the ``ag`` residual rides ``state["comm"]``).
        Returns ``(params, state)`` with the residuals advanced; the
        returned state is what :meth:`step` must then see (a skipped
        overflow step keeps the advanced ``ag`` residual — the gather
        consumed it on finite params, unlike the grad legs)."""
        residuals = None
        cfg = self.compression
        if (state is not None and cfg is not None
                and cfg.ici_legs and cfg.error_feedback):
            residuals = state.get("comm")
        params, new_res = self.layout.gather(
            shards, self.axis_name, compression=cfg,
            residuals=residuals,
            step=None if state is None else state["step"],
        )
        if new_res is not None and state is not None:
            state = dict(state)
            state["comm"] = new_res
        return params, state

    def unshard_params(self, global_shards, transform=None) -> Any:
        """Host-side: a ZeRO-3 checkpoint's flat shard buffer (the
        ``device_get`` of the placed shard array) → the full replicated
        param pytree — resume into a replicated-eval setup with this.
        Bit-identical to a full-width :meth:`gather_params`; under
        int8 gathers (``ici_legs``) the device view is the lossy wire
        format and this rebuild is the exact fp32 master, i.e. at
        least as accurate.

        ``transform`` is the checkpoint-load conversion seam: called
        ONCE on the rebuilt tree before anything is placed on device —
        e.g. ``lambda p: quantize_gpt_weights(p, "int8")`` to serve a
        trained checkpoint from a quantized weight pool without the
        full-width tree ever reaching HBM.  Quantization is a pure
        function of the weight bits and the rebuild is exact, so
        ``unshard → quantize`` is bit-identical to quantizing the
        replicated weights directly (pinned in
        tests/test_weight_quant.py)."""
        import numpy as _np

        params = self.layout.unshard(_np.asarray(global_shards))
        if transform is not None:
            params = transform(params)
        return params

    def init(self, params: Any) -> dict:
        """Build the sharded state — call inside shard_map with
        replicated params; each rank keeps only its flat shard
        (1/ici per device, replicated across dcn, when hierarchical).
        With ``param_specs`` given, data-axis-sharded leaves get a
        rank-local fp32 master + moments instead (see __init__).

        ZeRO-3 (``shard_params=True``): pass the flat param SHARD from
        :meth:`init_shards` instead — the state then holds only the
        moments (the shard itself is the master, threaded separately)
        plus the per-bucket comm residuals."""
        if self.shard_params:
            return self._init_zero3(params)
        local_tree = None
        if self._mask is not None:
            local_tree = self._mask_tree(params, self._mask, True)
            params = self._mask_tree(params, self._mask, False)
        world = _axis_size(self._shard_axis)
        rank = lax.axis_index(self._shard_axis)
        meta = _FlatMeta(params, world)
        flat = meta.flatten(params)
        local = lax.dynamic_slice(flat, (rank * meta.shard,), (meta.shard,))
        state = {"step": jnp.int32(0), "master": local}
        state.update(self._extra_init(meta.shard))
        if (self.compression is not None
                and self.compression.error_feedback):
            from apex_tpu.ops.quantization import init_residual

            state["comm"] = init_residual(
                meta.shard, _axis_size(self._cross_axis),
                self.compression.block_size,
            )
            if self.compression.ici_legs:
                # compensates the quantized grad reduce-scatter of the
                # full local flat buffer (one row per ici peer)
                state["comm"]["ici_push"] = jnp.zeros(
                    (meta.padded,), jnp.float32
                )
        if local_tree is not None:
            f32_tree = jax.tree.map(
                lambda x: jnp.asarray(x, jnp.float32), local_tree)
            state["local"] = {
                "master": f32_tree,
                **{k: jax.tree.map(jnp.zeros_like, f32_tree)
                   for k in self._extra_init(1)},
            }
        return state

    def _init_zero3(self, shards: jnp.ndarray) -> dict:
        """Moments + step (+ per-bucket residuals) for the flat shard;
        no ``master`` — the shard is the master."""
        shape = getattr(shards, "shape", None)
        if shape is None or len(shape) != 1 \
                or shape[0] != self.layout.shard_size:
            raise ValueError(
                f"init expected the ({self.layout.shard_size},) flat "
                f"param shard (from init_shards), got "
                f"{type(shards).__name__} of shape {shape} — in "
                "ZeRO-3 mode the state is built from the shard, not "
                "the replicated tree"
            )
        state = {"step": jnp.int32(0)}
        state.update(self._extra_init(self.layout.shard_size))
        if (self.compression is not None
                and self.compression.error_feedback):
            from apex_tpu.parallel.zero3 import zero3_comm_state

            state["comm"] = zero3_comm_state(
                self.layout, self.axis_name, self.compression
            )
        return state

    def step(
        self,
        state: dict,
        grads: Any,
        params: Any,
        lr: Optional[jnp.ndarray] = None,
        grads_finite: Optional[jnp.ndarray] = None,
        local_grads_prenormalized: bool = False,
    ) -> Tuple[Any, dict]:
        """reduce-scatter grads → sharded update → all-gather params.

        ``grads`` are the raw per-rank gradients — do NOT pre-psum them
        over dp; the reduce-scatter here replaces that all-reduce
        (reference: distributed_fused_adam.py overlapped RS+AR).
        Returns (new_params in model dtype, new_state).

        Data-axis-sharded leaves (``param_specs``): in the raw
        convention their grads are the backward all_to_all's SUM of
        every rank's contribution, so the local path divides by world
        to match the flat path's mean semantics.  If you hand grads
        that are ALREADY optimizer-ready for those leaves (e.g. the
        models' pipeline ``data_reduce`` convention, which applies the
        1/n itself), pass ``local_grads_prenormalized=True`` to skip
        the division.

        ZeRO-3 (``shard_params=True``): ``params`` is the flat
        ``(shard_size,)`` param shard (the fp32 master), ``grads`` the
        full per-rank gradient pytree from differentiating the
        gathered weights.  The grads reduce-scatter straight into the
        shard layout (int8 legs per the compression config), the
        update runs on the shard in place, and there is NO tail
        all-gather — the next step's :meth:`gather_params` is the
        gather.  Returns ``(new_shard, new_state)``.
        """
        if self.shard_params:
            return self._step_zero3(state, grads, params, lr,
                                    grads_finite)
        local_params = local_grads = None
        if self._mask is not None:
            local_params = self._mask_tree(params, self._mask, True)
            local_grads = self._mask_tree(grads, self._mask, True)
            params = self._mask_tree(params, self._mask, False)
            grads = self._mask_tree(grads, self._mask, False)
        world = _axis_size(self._shard_axis)
        rank = lax.axis_index(self._shard_axis)
        meta = _FlatMeta(params, world)
        lr = f32(self.lr if lr is None else lr)

        flat_grads = meta.flatten(grads)
        # mean-reduce-scatter: each rank receives its shard of the
        # dp-summed gradient.  Hierarchical: RS within ici, then AR of
        # the 1/ici shard across dcn (reference's 2-level pattern) —
        # optionally int8-quantized (``compression``; with ici_legs
        # the RS itself goes int8 too, chunk boundaries preserved so
        # the flat master layout is untouched)
        comm = state.get("comm")
        ici_legs = (self.compression is not None
                    and self.compression.ici_legs
                    and self._cross_axis is not None)
        # one base dither key per step, decorrelated per LEG: feeding
        # both quantization sites only step= would re-derive the SAME
        # key wherever a device's ici and dcn coordinates coincide
        # (the hazard _hierarchical_psum's leg_key fixes)
        rs_key = dcn_key = None
        if (ici_legs and self.compression.rounding == "stochastic"):
            base = jax.random.fold_in(jax.random.PRNGKey(0),
                                      state["step"])
            dcn_key = jax.random.fold_in(base, 0)
            rs_key = jax.random.fold_in(base, 1)
        new_ici_push = None
        if ici_legs:
            from apex_tpu.ops.quantization import (
                quantized_reduce_scatter,
            )

            g_local, new_ici_push = quantized_reduce_scatter(
                flat_grads, self._shard_axis, self.compression,
                residual=None if comm is None else comm["ici_push"],
                step=state["step"], key=rs_key,
            )
        else:
            g_local = lax.psum_scatter(
                flat_grads, self._shard_axis, tiled=True
            )
        total = world
        new_comm = None
        if self._cross_axis is not None:
            if self.compression is not None:
                from apex_tpu.ops.quantization import quantized_psum

                dcn_residual = None
                if comm is not None:
                    dcn_residual = {"push": comm["push"],
                                    "pull": comm["pull"]}
                g_local, new_comm = quantized_psum(
                    g_local, self._cross_axis, self.compression,
                    residual=dcn_residual, step=state["step"],
                    key=dcn_key,
                )
                if new_comm is not None and new_ici_push is not None:
                    new_comm = dict(new_comm)
                    new_comm["ici_push"] = new_ici_push
            else:
                g_local = lax.psum(g_local, self._cross_axis)
            total = world * _axis_size(self._cross_axis)
        g_local = g_local / total
        ids = meta.segment_ids()
        ids_local = lax.dynamic_slice(
            ids, (rank * meta.shard,), (meta.shard,)
        )

        new_step = state["step"] + 1
        extra = {
            k: v for k, v in state.items()
            if k not in ("step", "master", "comm")
        }
        new_master, new_extra = self._update_shard(
            extra, new_step, g_local, state["master"], lr, meta, ids_local
        )

        new_state = dict(new_extra)
        new_state["step"] = new_step
        new_state["master"] = new_master
        if new_comm is not None:
            # grads_finite=False reverts this with the rest of the
            # state below (tree_where): a skipped step must not absorb
            # the overflow garbage into the error-feedback residual
            new_state["comm"] = new_comm
        if local_params is not None:
            # rank-local update of the data-axis-sharded leaves: no
            # collectives — their grads are already complete on the
            # owning rank (the MoE backward all_to_all accumulated
            # every token's contribution into the expert's owner)
            lextra = {k: v for k, v in state["local"].items()
                      if k != "master"}
            lscale = (1.0 if local_grads_prenormalized
                      else 1.0 / _axis_size(self._shard_axis))
            lgrads = jax.tree.map(
                lambda g: jnp.asarray(g, jnp.float32) * lscale,
                local_grads)
            new_lmaster, new_lextra = self._local_update(
                lextra, new_step, lgrads, state["local"]["master"], lr)
            new_state["local"] = {"master": new_lmaster, **new_lextra}
        if grads_finite is not None:
            new_state = tree_where(grads_finite, new_state, state)
            new_master = new_state["master"]

        send = new_master
        if self.compressed_allgather == "bf16":
            send = send.astype(jnp.bfloat16)
        elif self.compressed_allgather == "e5m2":
            send = send.astype(jnp.float8_e5m2)
        # unflatten casts each leaf to its model dtype, so no
        # intermediate fp32 expansion of the gathered buffer is needed
        flat_params = all_gather_invariant(
            send, self._shard_axis, axis=0, tiled=True
        )
        new_params = meta.unflatten(flat_params)
        if local_params is not None:
            local_out = jax.tree.map(
                lambda m, p: m.astype(jnp.asarray(p).dtype),
                new_state["local"]["master"], local_params,
            )
            new_params = jax.tree.map(
                lambda is_local, a, b: b if is_local else a,
                self._mask, new_params, local_out,
            )
        return new_params, new_state

    def _step_zero3(self, state, grads, shards, lr, grads_finite):
        """RS grads into the shard → in-place sharded update; the
        reverted-on-overflow set is the grad-leg residuals and the
        moments (the ``ag`` residual in the input state was advanced
        by this step's gather on FINITE params and must survive the
        skip)."""
        layout = self.layout
        world = _axis_size(self._shard_axis)
        total = world
        if self._cross_axis is not None:
            total = world * _axis_size(self._cross_axis)
        lr = f32(self.lr if lr is None else lr)
        comm = state.get("comm")
        g_shard, new_comm = layout.reduce_scatter_grads(
            grads, self.axis_name, compression=self.compression,
            residuals=comm, step=state["step"],
        )
        g_shard = g_shard / total
        rank = lax.axis_index(self._shard_axis)
        ids_local = layout.local_segment_ids(rank)
        new_step = state["step"] + 1
        extra = {
            k: v for k, v in state.items()
            if k not in ("step", "comm")
        }
        new_shard, new_extra = self._update_shard(
            extra, new_step, g_shard, shards, lr, layout, ids_local
        )
        new_state = dict(new_extra)
        new_state["step"] = new_step
        if new_comm is not None:
            new_state["comm"] = new_comm
        elif comm is not None:
            new_state["comm"] = comm
        if grads_finite is not None:
            new_state = tree_where(grads_finite, new_state, state)
            new_shard = tree_where(grads_finite, new_shard, shards)
        return new_shard, new_state


class DistributedFusedAdam(_DistributedOptimizer):
    """Sharded Adam/AdamW
    (reference: apex/contrib/optimizers/distributed_fused_adam.py)."""

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        axis_name: Any = DATA_PARALLEL_AXIS,
        compressed_allgather: Optional[str] = None,
        param_specs: Any = None,
        compression: Any = None,
        shard_params: bool = False,
        bucket_bytes: Optional[int] = None,
    ):
        super().__init__(lr=lr, axis_name=axis_name,
                         compressed_allgather=compressed_allgather,
                         param_specs=param_specs,
                         compression=compression,
                         shard_params=shard_params,
                         bucket_bytes=bucket_bytes)
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay

    def _update_shard(self, extra, step, g, p, lr, meta, ids_local):
        b1, b2 = f32(self.beta1), f32(self.beta2)
        stepf = step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - b1 ** stepf
            bc2 = 1.0 - b2 ** stepf
        else:
            bc1 = bc2 = jnp.float32(1.0)
        wd = f32(self.weight_decay)
        if not self.adam_w_mode and self.weight_decay != 0.0:
            g = g + wd * p
        m = b1 * extra["exp_avg"] + (1.0 - b1) * g
        v = b2 * extra["exp_avg_sq"] + (1.0 - b2) * jnp.square(g)
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode and self.weight_decay != 0.0:
            update = update + wd * p
        return p - lr * update, {"exp_avg": m, "exp_avg_sq": v}

    def _local_update(self, extra, step, g, p, lr):
        """Adam on the rank-local (data-axis-sharded) leaves — the
        identical elementwise math as :meth:`_update_shard`, applied
        per leaf (Adam has no cross-leaf coupling, so locality is
        exact; the strict zip errors on any leaf-count mismatch)."""
        flat_p, treedef = jax.tree_util.tree_flatten(p)
        out_p, out_m, out_v = [], [], []
        for pi, gi, mi, vi in zip(
            flat_p, jax.tree.leaves(g), jax.tree.leaves(extra["exp_avg"]),
            jax.tree.leaves(extra["exp_avg_sq"]), strict=True,
        ):
            npi, upd = self._update_shard(
                {"exp_avg": mi, "exp_avg_sq": vi}, step, gi, pi, lr,
                meta=None, ids_local=None,
            )
            out_p.append(npi)
            out_m.append(upd["exp_avg"])
            out_v.append(upd["exp_avg_sq"])
        unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
        return unf(out_p), {"exp_avg": unf(out_m),
                            "exp_avg_sq": unf(out_v)}


class DistributedFusedLAMB(_DistributedOptimizer):
    """Sharded LAMB with exact per-parameter trust ratios
    (reference: apex/contrib/optimizers/distributed_fused_lamb.py:10-910;
    step at :836).  Per-parameter norms are assembled from shard-local
    segment sums + a psum, so sharding does not change the math."""

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        axis_name: Any = DATA_PARALLEL_AXIS,
        compressed_allgather: Optional[str] = None,
        param_specs: Any = None,
        compression: Any = None,
        shard_params: bool = False,
        bucket_bytes: Optional[int] = None,
    ):
        super().__init__(lr=lr, axis_name=axis_name,
                         compressed_allgather=compressed_allgather,
                         param_specs=param_specs,
                         compression=compression,
                         shard_params=shard_params,
                         bucket_bytes=bucket_bytes)
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def _segment_norms(self, x, ids_local, meta):
        """Global per-parameter L2 norms of a sharded flat vector."""
        partial = jax.ops.segment_sum(
            jnp.square(x), ids_local, num_segments=meta.num_leaves + 1
        )
        # shards are over the shard axis only (replicated across
        # dcn when hierarchical), so one psum reassembles the norm
        return jnp.sqrt(lax.psum(partial, self._shard_axis))

    def _update_shard(self, extra, step, g, p, lr, meta, ids_local):
        b1, b2 = f32(self.beta1), f32(self.beta2)
        beta3 = 1.0 - b1 if self.grad_averaging else jnp.float32(1.0)
        stepf = step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - b1 ** stepf
            bc2 = 1.0 - b2 ** stepf
        else:
            bc1 = bc2 = jnp.float32(1.0)
        wd = f32(self.weight_decay)

        # global grad-norm clip (clip-after-reduce, the reference's
        # `_clip_after_ar` default path)
        gnorm = jnp.sqrt(
            lax.psum(jnp.sum(jnp.square(g)), self._shard_axis)
        )
        if self.max_grad_norm is not None and self.max_grad_norm > 0:
            clip = jnp.where(
                gnorm > self.max_grad_norm, self.max_grad_norm / gnorm, 1.0
            )
        else:
            clip = jnp.float32(1.0)
        g = g * clip
        if not self.adam_w_mode and self.weight_decay != 0.0:
            # MOMENT_MODE_0 (classic/L2): decay folds into the gradient
            # *before* the moment updates (multi_tensor_lamb.cu).
            g = g + wd * p

        m = b1 * extra["exp_avg"] + beta3 * g
        v = b2 * extra["exp_avg_sq"] + (1.0 - b2) * jnp.square(g)
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode and self.weight_decay != 0.0:
            update = update + wd * p

        w_norms = self._segment_norms(p, ids_local, meta)
        u_norms = self._segment_norms(update, ids_local, meta)
        if self.weight_decay == 0.0 and not self.use_nvlamb:
            trust_per_leaf = jnp.ones_like(w_norms)
        else:
            trust_per_leaf = jnp.where(
                (w_norms > 0) & (u_norms > 0),
                w_norms / jnp.maximum(u_norms, 1e-30),
                1.0,
            )
        trust = trust_per_leaf[ids_local]
        return p - lr * trust * update, {"exp_avg": m, "exp_avg_sq": v}

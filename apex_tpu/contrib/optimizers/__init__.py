"""Distributed (ZeRO-style) optimizers
(reference: apex/contrib/optimizers/)."""

from apex_tpu.contrib.optimizers.distributed import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
    reestablish_replicated,
)

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB",
           "reestablish_replicated"]

"""Whole-pytree tensor-list primitives.

The reference's ``multi_tensor_apply`` engine exists to amortize kernel
launches: one CUDA launch processes chunks of up to 110 tensors, with a
``noop_flag`` overflow buffer for sync-free loss scaling
(reference: csrc/multi_tensor_apply.cuh:16-147,
apex/multi_tensor_apply/multi_tensor_apply.py:3-30).

Under XLA there is no per-tensor launch cost to amortize — a jitted
function over a whole pytree compiles to a handful of fused loops.  So the
TPU-native "multi tensor apply" is simply: express the op over the pytree,
jit it once.  These functions keep the reference's *semantics* (including
the overflow flag) with none of its machinery, and are the building blocks
the fused optimizers and the scaler share.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp

__all__ = [
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "global_l2norm",
    "multi_tensor_applier",
]


def _float_leaves(tree):
    return [
        l
        for l in jax.tree.leaves(tree)
        if hasattr(l, "dtype") and jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
    ]


def multi_tensor_scale(
    tree: Any, scale: Union[float, jnp.ndarray], out_dtype: Optional[jnp.dtype] = None
) -> Tuple[Any, jnp.ndarray]:
    """``out = in * scale`` over every leaf, plus an all-finite flag.

    Equivalent of ``amp_C.multi_tensor_scale``
    (reference: csrc/multi_tensor_scale_kernel.cu).  Returns
    ``(scaled_tree, overflow)`` where overflow is True if any *input* leaf
    contained inf/nan (the kernel's noop_flag contract: it checks the
    incoming values it reads — a non-finite value INTRODUCED by the
    multiply, e.g. an inf ``scale``, does not raise the flag, exactly
    like the CUDA kernel's per-element ``isfinite(r_in[ii])``).

    The finiteness reduction runs on the same fp32 cast the multiply
    uses (the half-dtype → fp32 cast is exact, so finiteness is
    preserved), so the jitted op reads each leaf ONCE — the check fuses
    into the scaling loop instead of a second pass over every input.
    """
    flags = []

    def scale_leaf(l):
        arr = jnp.asarray(l)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            return l
        xf = arr.astype(jnp.float32)
        flags.append(jnp.all(jnp.isfinite(xf)))
        out = xf * scale
        return out.astype(out_dtype or arr.dtype)

    scaled = jax.tree.map(scale_leaf, tree)
    if flags:
        overflow = ~jnp.stack(flags).all()
    else:
        overflow = jnp.bool_(False)
    return scaled, overflow


def multi_tensor_axpby(
    a: Union[float, jnp.ndarray],
    x_tree: Any,
    b: Union[float, jnp.ndarray],
    y_tree: Any,
    out_dtype: Optional[jnp.dtype] = None,
) -> Tuple[Any, jnp.ndarray]:
    """``out = a*x + b*y`` leafwise with an overflow flag
    (reference: csrc/multi_tensor_axpby_kernel.cu) — the kernel behind
    stashed-gradient accumulation in amp
    (reference: apex/amp/_process_optimizer.py:93-139).

    The flag checks the INCOMING x/y values (on the same single fp32
    read the axpby consumes — one pass per leaf, like
    :func:`multi_tensor_scale`); non-finite values introduced by the
    coefficients alone do not raise it."""
    flags = []

    def axpby(x, y):
        xa, ya = jnp.asarray(x), jnp.asarray(y)
        xf = xa.astype(jnp.float32)
        yf = ya.astype(jnp.float32)
        if jnp.issubdtype(xa.dtype, jnp.floating):
            flags.append(jnp.all(jnp.isfinite(xf)))
        if jnp.issubdtype(ya.dtype, jnp.floating):
            flags.append(jnp.all(jnp.isfinite(yf)))
        out = a * xf + b * yf
        return out.astype(out_dtype or xa.dtype)

    out = jax.tree.map(axpby, x_tree, y_tree)
    if flags:
        overflow = ~jnp.stack(flags).all()
    else:
        overflow = jnp.bool_(False)
    return out, overflow


def multi_tensor_l2norm(
    tree: Any, per_tensor: bool = False
) -> Union[jnp.ndarray, Tuple[jnp.ndarray, list]]:
    """Global (and optionally per-leaf) L2 norm in fp32 accumulation
    (reference: csrc/multi_tensor_l2norm_kernel.cu), used by FusedLAMB's
    global grad norm (reference: apex/optimizers/fused_lamb.py:107-137)."""
    leaves = _float_leaves(tree)
    if not leaves:
        zero = jnp.float32(0.0)
        return (zero, []) if per_tensor else zero
    sq = [jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves]
    total = jnp.sqrt(jnp.stack(sq).sum())
    if per_tensor:
        return total, [jnp.sqrt(s) for s in sq]
    return total


def global_l2norm(tree: Any) -> jnp.ndarray:
    return multi_tensor_l2norm(tree, per_tensor=False)


class multi_tensor_applier:
    """API-compat shim for code written against the reference dispatcher
    (reference: apex/multi_tensor_apply/multi_tensor_apply.py:3-30).

    ``op`` is any callable taking/returning pytrees; chunking is
    irrelevant under XLA so ``chunk_size`` is accepted and ignored.
    """

    available = True

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag_buffer, tensor_lists, *args):
        return op(tensor_lists, *args)

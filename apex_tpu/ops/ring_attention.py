"""Ring attention: exact attention over a context-parallel mesh axis.

This is new TPU-first capability beyond the reference (SURVEY.md §2.3:
"No ring-attention / Ulysses / context parallelism exists in this
snapshot" — the nearest analog is the SpatialBottleneck halo exchange,
reference: apex/contrib/bottleneck/bottleneck.py:218-385).  The sequence
dimension is sharded over the "cp" axis; K/V shards rotate around the
ring with ``ppermute`` while every rank accumulates its queries' online
softmax — after ``cp`` steps each query has attended to the full global
sequence, with per-chip memory O(S/cp) and the K/V transfer overlapping
the attention compute of the previous block (XLA's latency-hiding
scheduler handles the overlap; the ring pattern rides neighbour ICI
links by construction).

Causality uses global position ids, so rank boundaries are invisible to
the math: the result equals dense causal attention on the gathered
sequence (tested to 1e-5).

Backward falls out of autodiff through the scan: cotangents ride the
reverse ring.  ``remat=True`` recomputes each block's scores in the
backward pass instead of saving cp score matrices.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import CONTEXT_PARALLEL_AXIS
from apex_tpu._compat import axis_size as _axis_size

__all__ = ["ring_attention", "ring_attention_reference"]

_NEG = -1e30


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = CONTEXT_PARALLEL_AXIS,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    remat: bool = True,
    block_k: int = 512,
) -> jnp.ndarray:
    """Attention over the global sequence from per-rank shards.

    ``q``, ``k``, ``v``: (batch, heads, s_local, head_dim) — the local
    contiguous shard of a sequence of length ``cp * s_local``.  Call
    inside ``shard_map`` with the sequence dim sharded over ``axis_name``.
    Returns the local shard of the attention output.

    ``block_k`` chunks the inner K walk of each ring step so peak score
    memory is (s_local × block_k), not (s_local × s_local) — the
    flash-attention trade, expressed in XLA, which keeps long-context
    shards (s_local ≫ 1k) inside VMEM-friendly working sets.
    """
    b, h, s_local, d = q.shape
    scale = (1.0 / d**0.5) if sm_scale is None else float(sm_scale)
    cp = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    bk = min(block_k, s_local)
    if s_local % bk:
        bk = s_local  # irregular shard: fall back to one chunk
    n_chunks = s_local // bk

    q32 = q.astype(jnp.float32) * scale
    qpos = rank * s_local + jnp.arange(s_local)

    def attend(i, k_blk, v_blk, acc, m, l):
        src = (rank - i) % cp  # whose K/V shard we currently hold

        def kchunk(carry, j):
            acc, m, l = carry
            kc = lax.dynamic_slice_in_dim(k_blk, j * bk, bk, axis=2)
            vc = lax.dynamic_slice_in_dim(v_blk, j * bk, bk, axis=2)
            kpos = src * s_local + j * bk + jnp.arange(bk)
            s = jnp.einsum(
                "bhqd,bhkd->bhqk", q32, kc.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            if causal:
                s = jnp.where(kpos[None, None, None, :] >
                              qpos[None, None, :, None], _NEG, s)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * corr + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return (acc_new, m_new, l_new), None

        if n_chunks == 1:
            (acc, m, l), _ = kchunk((acc, m, l), 0)
        else:
            (acc, m, l), _ = lax.scan(
                kchunk, (acc, m, l), jnp.arange(n_chunks)
            )
        return acc, m, l

    attend_fn = jax.checkpoint(attend) if remat else attend

    def block(carry, i):
        k_blk, v_blk, acc, m, l = carry
        acc, m, l = attend_fn(i, k_blk, v_blk, acc, m, l)
        # rotate K/V one step around the ring
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, acc, m, l), None

    # build the accumulators from q so they carry its varying-axes type
    # (a plain zeros constant would mismatch the scan carry under
    # shard_map's vma checking)
    zero_q = q32 * 0
    acc0 = zero_q
    m0 = jnp.sum(zero_q, axis=-1, keepdims=True) + _NEG
    l0 = jnp.sum(zero_q, axis=-1, keepdims=True)
    # scan the first cp-1 blocks (each ends with a rotation), then attend
    # the final block outside the loop — a rotation there would only
    # carry K/V back to where they started, and XLA cannot DCE a
    # collective inside the loop body
    (k_last, v_last, acc, m, l), _ = lax.scan(
        block, (k, v, acc0, m0, l0), jnp.arange(cp - 1)
    )
    acc, m, l = attend_fn(cp - 1, k_last, v_last, acc, m, l)
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention_reference(q, k, v, causal=False, sm_scale=None):
    """Dense single-device reference (for tests): plain attention on the
    full gathered sequence."""
    from apex_tpu.ops.attention import mha_reference

    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)

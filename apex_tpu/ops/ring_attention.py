"""Ring attention: exact attention over a context-parallel mesh axis.

This is new TPU-first capability beyond the reference (SURVEY.md §2.3:
"No ring-attention / Ulysses / context parallelism exists in this
snapshot" — the nearest analog is the SpatialBottleneck halo exchange,
reference: apex/contrib/bottleneck/bottleneck.py:218-385).  The sequence
dimension is sharded over the "cp" axis; K/V shards rotate around the
ring with ``ppermute`` while every rank accumulates its queries' online
softmax — after ``cp`` steps each query has attended to the full global
sequence, with per-chip memory O(S/cp) and the K/V transfer overlapping
the attention compute of the previous block (XLA's latency-hiding
scheduler handles the overlap; the ring pattern rides neighbour ICI
links by construction).

Causality uses global position ids, so rank boundaries are invisible to
the math: the result equals dense causal attention on the gathered
sequence (tested to 1e-5).

Backward falls out of autodiff through the scan: cotangents ride the
reverse ring.  ``remat=True`` recomputes each block's scores in the
backward pass instead of saving cp score matrices.

Per-shard inner attention (``attention_impl``): the default inline XLA
walk materialises (s_local, block_k) score chunks on the VPU.
``attention_impl`` routes each ring step's block attention through the
kernel dispatch family instead (``ops/attention_mid.py`` with
``return_lse=True`` — the pipelined kernel whose fused backward carries
a real lse cotangent), merging the per-block (out, lse) pairs by
log-sum-exp outside the kernel.  A ring block is globally either fully
visible (source shard strictly before this rank), exactly causal (the
diagonal shard), or fully masked (after this rank) — so causality needs
no global position plumbing into the kernel, and fully-masked shards
are SKIPPED outright (the ring-granularity analog of the kernel's
causal block-skip; the inline path computes and masks them).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import CONTEXT_PARALLEL_AXIS
from apex_tpu._compat import axis_size as _axis_size

__all__ = ["ring_attention", "ring_attention_reference"]

_NEG = -1e30


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = CONTEXT_PARALLEL_AXIS,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    remat: bool = True,
    block_k: int = 512,
    attention_impl: Optional[str] = None,
) -> jnp.ndarray:
    """Attention over the global sequence from per-rank shards.

    ``q``, ``k``, ``v``: (batch, heads, s_local, head_dim) — the local
    contiguous shard of a sequence of length ``cp * s_local``.  Call
    inside ``shard_map`` with the sequence dim sharded over ``axis_name``.
    Returns the local shard of the attention output.

    ``block_k`` chunks the inner K walk of each ring step so peak score
    memory is (s_local × block_k), not (s_local × s_local) — the
    flash-attention trade, expressed in XLA, which keeps long-context
    shards (s_local ≫ 1k) inside VMEM-friendly working sets.

    ``attention_impl``: ``None`` keeps the inline XLA walk (bit-exact
    with previous releases).  ``"mid"``/``"short"``/``"pallas"`` run
    each ring block through the pipelined fmha-mid kernel (per-shard
    lengths sit squarely in its window) and ``"xla"`` through its
    reference path — an A/B comparator for the merge math that
    materializes (s_local, s_local) scores per ring step, so prefer
    ``None`` for production XLA runs — both via the lse-merge
    formulation, which also
    SKIPS fully-masked source shards under causal (``block_k`` is then
    unused; the kernel blocks internally).  On jax 0.4.x the Pallas
    variants need the enclosing ``shard_map`` built with
    ``check_rep=False`` (pallas_call has no replication rule there;
    newer jax type-checks via the vma-aware ``shape_struct``).
    """
    b, h, s_local, d = q.shape
    scale = (1.0 / d**0.5) if sm_scale is None else float(sm_scale)
    if attention_impl is not None:
        return _ring_attention_merge(
            q, k, v, axis_name, causal, scale, remat, attention_impl
        )
    cp = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    bk = min(block_k, s_local)
    if s_local % bk:
        bk = s_local  # irregular shard: fall back to one chunk
    n_chunks = s_local // bk

    q32 = q.astype(jnp.float32) * scale
    qpos = rank * s_local + jnp.arange(s_local)

    def attend(i, k_blk, v_blk, acc, m, l):
        src = (rank - i) % cp  # whose K/V shard we currently hold

        def kchunk(carry, j):
            acc, m, l = carry
            kc = lax.dynamic_slice_in_dim(k_blk, j * bk, bk, axis=2)
            vc = lax.dynamic_slice_in_dim(v_blk, j * bk, bk, axis=2)
            kpos = src * s_local + j * bk + jnp.arange(bk)
            s = jnp.einsum(
                "bhqd,bhkd->bhqk", q32, kc.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            if causal:
                s = jnp.where(kpos[None, None, None, :] >
                              qpos[None, None, :, None], _NEG, s)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * corr + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return (acc_new, m_new, l_new), None

        if n_chunks == 1:
            (acc, m, l), _ = kchunk((acc, m, l), 0)
        else:
            (acc, m, l), _ = lax.scan(
                kchunk, (acc, m, l), jnp.arange(n_chunks)
            )
        return acc, m, l

    attend_fn = jax.checkpoint(attend) if remat else attend

    def block(carry, i):
        k_blk, v_blk, acc, m, l = carry
        acc, m, l = attend_fn(i, k_blk, v_blk, acc, m, l)
        # rotate K/V one step around the ring
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, acc, m, l), None

    # build the accumulators from q so they carry its varying-axes type
    # (a plain zeros constant would mismatch the scan carry under
    # shard_map's vma checking)
    zero_q = q32 * 0
    acc0 = zero_q
    m0 = jnp.sum(zero_q, axis=-1, keepdims=True) + _NEG
    l0 = jnp.sum(zero_q, axis=-1, keepdims=True)
    # scan the first cp-1 blocks (each ends with a rotation), then attend
    # the final block outside the loop — a rotation there would only
    # carry K/V back to where they started, and XLA cannot DCE a
    # collective inside the loop body
    (k_last, v_last, acc, m, l), _ = lax.scan(
        block, (k, v, acc0, m0, l0), jnp.arange(cp - 1)
    )
    acc, m, l = attend_fn(cp - 1, k_last, v_last, acc, m, l)
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def _ring_attention_merge(q, k, v, axis_name, causal, scale, remat, impl):
    """Kernel-backed ring attention: per-shard (out, lse) blocks merged
    by log-sum-exp.

    Each ring step attends the local queries against one source shard's
    K/V via :func:`apex_tpu.ops.attention_mid.fmha_mid` with
    ``return_lse=True`` — globally the block is fully visible, exactly
    causal (diagonal shard, i == 0), or fully masked (skipped), so the
    kernel's own ``causal`` flag expresses the mask without global
    position plumbing.  Gradients flow through the merge weights and
    the kernel's fused backward (which consumes the real lse
    cotangent); the ring itself unrolls over the static ``cp``.
    """
    from apex_tpu.ops.attention_mid import fmha_mid

    if impl not in ("mid", "short", "pallas", "xla"):
        raise ValueError(
            f"unknown ring attention_impl {impl!r}; expected None, "
            "'mid'/'short'/'pallas', or 'xla'"
        )
    kernel_impl = "xla" if impl == "xla" else "pallas"
    cp = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def attend(q, k_blk, v_blk, causal_blk):
        out, lse = fmha_mid(
            q, k_blk, v_blk, causal=causal_blk, sm_scale=scale,
            implementation=kernel_impl, return_lse=True,
        )
        return out.astype(jnp.float32), lse

    if remat:
        attend = jax.checkpoint(attend, static_argnums=(3,))

    def skip_block(q, k_blk, v_blk):
        # zero contribution with lse = -inf-ish; built from the real
        # operands (times zero) so both cond branches carry the same
        # mesh-varying type under shard_map's vma checking
        pad = (jnp.sum(k_blk.astype(jnp.float32))
               + jnp.sum(v_blk.astype(jnp.float32))) * 0.0
        z = q.astype(jnp.float32) * 0.0 + pad
        return z, jnp.sum(z, axis=-1) + _NEG

    acc = q.astype(jnp.float32) * 0.0                 # (b, h, s, d)
    lse_acc = jnp.sum(acc, axis=-1) + _NEG            # (b, h, s)
    k_blk, v_blk = k, v
    for i in range(cp):
        if causal and i > 0:
            # source shard is rank - i mod cp: globally before this
            # rank's rows iff rank >= i (fully visible), else after
            # (fully masked — skip the block outright)
            out_i, lse_i = lax.cond(
                rank >= i,
                lambda q, kb, vb: attend(q, kb, vb, False),
                skip_block,
                q, k_blk, v_blk,
            )
        else:
            out_i, lse_i = attend(q, k_blk, v_blk, causal and i == 0)
        m = jnp.maximum(lse_acc, lse_i)
        w_acc = jnp.exp(lse_acc - m)
        w_new = jnp.exp(lse_i - m)
        tot = w_acc + w_new
        acc = (acc * w_acc[..., None] + out_i * w_new[..., None]) \
            / tot[..., None]
        lse_acc = m + jnp.log(tot)
        if i != cp - 1:
            # rotate K/V one step around the ring; the final block's
            # rotation would only return them to their origin
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
    return acc.astype(q.dtype)


def ring_attention_reference(q, k, v, causal=False, sm_scale=None):
    """Dense single-device reference (for tests): plain attention on the
    full gathered sequence."""
    from apex_tpu.ops.attention import mha_reference

    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)

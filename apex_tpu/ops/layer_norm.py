"""Fused LayerNorm / RMSNorm kernels.

Capability match for the reference's ``fused_layer_norm_cuda`` and
``fast_layer_norm`` extensions (reference: csrc/layer_norm_cuda_kernel.cu,
apex/contrib/csrc/layer_norm/) re-designed for TPU:

- statistics in fp32 regardless of input dtype (the kernels' accumulation
  contract),
- one ``custom_vjp`` shared by the Pallas TPU kernel and the XLA fallback
  so both paths are numerically interchangeable,
- the "mixed dtype" Megatron variant (input dtype ≠ param dtype, output
  follows the input, reference: csrc/layer_norm_cuda.cpp
  ``forward_affine_mixed_dtypes``).

The Pallas forward tiles rows into VMEM blocks and keeps the (mean,
invvar) residuals for the backward; dgamma/dbeta are column reductions
XLA already does optimally, so only dx runs in Pallas.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.ops.common import run_kernel, shape_struct

from apex_tpu.utils.platform import is_tpu

try:  # imported lazily on CPU-only hosts that lack Mosaic
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None

__all__ = [
    "fused_layer_norm",
    "fused_layer_norm_affine",
    "fused_rms_norm",
    "fused_rms_norm_affine",
    "mixed_dtype_fused_layer_norm_affine",
]


def _norm_size(normalized_shape: Union[int, Sequence[int]]) -> int:
    if isinstance(normalized_shape, int):
        return normalized_shape
    size = 1
    for s in normalized_shape:
        size *= int(s)
    return size


def _as_2d(x: jnp.ndarray, hidden: int) -> jnp.ndarray:
    return x.reshape(-1, hidden)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _ln_fwd_kernel(x_ref, o_ref, mean_ref, invvar_ref, *, eps, rms):
    x = x_ref[:].astype(jnp.float32)
    if rms:
        mean = jnp.zeros((x.shape[0], 1), jnp.float32)
    else:
        mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    invvar = jax.lax.rsqrt(var + eps)
    o_ref[:] = ((x - mean) * invvar).astype(o_ref.dtype)
    # stats are written as (grid, 1, block_rows) — the singleton keeps
    # the trailing block dims equal to the array dims, which frees
    # block_rows from the 128-lane tiling/alignment rules so large
    # hidden sizes can use small row blocks without blowing VMEM
    mean_ref[0, 0, :] = mean[:, 0]
    invvar_ref[0, 0, :] = invvar[:, 0]


def _ln_fwd_pallas(x2d: jnp.ndarray, eps: float, rms: bool):
    rows, hidden = x2d.shape
    # block sized so in+out+fp32 intermediates stay well under the 16 MB
    # VMEM scope: ~2 MB of fp32 per block buffer
    cap = max(8, (512 * 1024) // max(hidden, 1) // 8 * 8)
    block_rows = max(8, min(cap, min(256, rows)))
    # pad rows to a multiple of block_rows
    pad = (-rows) % block_rows
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    padded_rows = rows + pad
    grid = (padded_rows // block_rows,)
    out, mean, invvar = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps, rms=rms),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_rows), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, block_rows), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            shape_struct((padded_rows, hidden), x2d.dtype, x2d),
            shape_struct((grid[0], 1, block_rows), jnp.float32, x2d),
            shape_struct((grid[0], 1, block_rows), jnp.float32, x2d),
        ],
        # interpreter mode off-TPU so the kernel body stays testable
        interpret=not is_tpu(),
    )(x2d)
    mean = mean.reshape(padded_rows)
    invvar = invvar.reshape(padded_rows)
    if pad:
        out, mean, invvar = out[:rows], mean[:rows], invvar[:rows]
    return out, mean, invvar


def _ln_fwd_xla(x2d: jnp.ndarray, eps: float, rms: bool):
    xf = x2d.astype(jnp.float32)
    if rms:
        mean = jnp.zeros((xf.shape[0],), jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1)
    else:
        mean = jnp.mean(xf, axis=-1)
        var = jnp.mean(jnp.square(xf - mean[:, None]), axis=-1)
    invvar = jax.lax.rsqrt(var + eps)
    xhat = (xf - mean[:, None]) * invvar[:, None]
    return xhat.astype(x2d.dtype), mean, invvar


def _ln_fwd(x2d, eps, rms, implementation: Optional[str]):
    # Auto mode routes to XLA *by measurement*: layernorm is bandwidth-
    # bound and XLA's fused mean/var/normalize pipeline beats the Pallas
    # tile kernel on every swept shape (0.7-1.0x, KERNELS_TPU.json).
    # The kernel stays available via implementation='pallas' for the
    # cross-check tier.
    return run_kernel(
        "fused_layer_norm",
        lambda: _ln_fwd_pallas(x2d, eps, rms),
        lambda: _ln_fwd_xla(x2d, eps, rms),
        implementation,
        implementation or "xla",
    )


# ---------------------------------------------------------------------------
# custom_vjp core (normalize-only; affine applied outside so one vjp serves
# affine / non-affine / mixed-dtype variants)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _normalize(x2d, eps: float, rms: bool, implementation: Optional[str]):
    out, _, _ = _ln_fwd(x2d, eps, rms, implementation)
    return out


def _normalize_fwd(x2d, eps, rms, implementation):
    out, mean, invvar = _ln_fwd(x2d, eps, rms, implementation)
    return out, (x2d, mean, invvar)


def _normalize_bwd(eps, rms, implementation, res, dxhat):
    x2d, mean, invvar = res
    xf = x2d.astype(jnp.float32)
    dy = dxhat.astype(jnp.float32)
    xhat = (xf - mean[:, None]) * invvar[:, None]
    n = xf.shape[-1]
    if rms:
        # dx = invvar*(dy - xhat * mean(dy*xhat))
        c2 = jnp.mean(dy * xhat, axis=-1, keepdims=True)
        dx = invvar[:, None] * (dy - xhat * c2)
    else:
        c1 = jnp.mean(dy, axis=-1, keepdims=True)
        c2 = jnp.mean(dy * xhat, axis=-1, keepdims=True)
        dx = invvar[:, None] * (dy - c1 - xhat * c2)
    return (dx.astype(x2d.dtype),)


_normalize.defvjp(_normalize_fwd, _normalize_bwd)


# ---------------------------------------------------------------------------
# public functional API
# ---------------------------------------------------------------------------


def fused_layer_norm(
    x: jnp.ndarray,
    normalized_shape: Union[int, Sequence[int]],
    eps: float = 1e-5,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """Non-affine fused layer norm (reference: ``FusedLayerNormFunction``)."""
    hidden = _norm_size(normalized_shape)
    shape = x.shape
    xhat = _normalize(_as_2d(x, hidden), eps, False, implementation)
    return xhat.reshape(shape)


def fused_layer_norm_affine(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray,
    normalized_shape: Union[int, Sequence[int]],
    eps: float = 1e-5,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """Affine fused layer norm (reference: ``FusedLayerNormAffineFunction``).

    Output dtype follows the input; affine math runs in fp32.
    """
    hidden = _norm_size(normalized_shape)
    shape = x.shape
    xhat = _normalize(_as_2d(x, hidden), eps, False, implementation)
    out = (
        xhat.astype(jnp.float32) * weight.reshape(-1).astype(jnp.float32)
        + bias.reshape(-1).astype(jnp.float32)
    )
    return out.astype(x.dtype).reshape(shape)


def mixed_dtype_fused_layer_norm_affine(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray,
    normalized_shape: Union[int, Sequence[int]],
    eps: float = 1e-5,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """Megatron "mixed dtypes" variant: input dtype may differ from param
    dtype; output follows the *weight* dtype (reference:
    apex/normalization/fused_layer_norm.py ``MixedFusedLayerNorm`` via
    ``forward_affine_mixed_dtypes``)."""
    hidden = _norm_size(normalized_shape)
    shape = x.shape
    xhat = _normalize(_as_2d(x, hidden), eps, False, implementation)
    out = (
        xhat.astype(jnp.float32) * weight.reshape(-1).astype(jnp.float32)
        + bias.reshape(-1).astype(jnp.float32)
    )
    return out.astype(weight.dtype).reshape(shape)


def fused_rms_norm(
    x: jnp.ndarray,
    normalized_shape: Union[int, Sequence[int]],
    eps: float = 1e-5,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    hidden = _norm_size(normalized_shape)
    shape = x.shape
    xhat = _normalize(_as_2d(x, hidden), eps, True, implementation)
    return xhat.reshape(shape)


def fused_rms_norm_affine(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    normalized_shape: Union[int, Sequence[int]],
    eps: float = 1e-5,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    hidden = _norm_size(normalized_shape)
    shape = x.shape
    xhat = _normalize(_as_2d(x, hidden), eps, True, implementation)
    out = xhat.astype(jnp.float32) * weight.reshape(-1).astype(jnp.float32)
    return out.astype(x.dtype).reshape(shape)

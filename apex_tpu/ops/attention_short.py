"""Short-sequence attention (fmha-short): single-pass Pallas kernels.

The flash kernel in ``ops/attention.py`` is built for long sequences:
a 3-D grid with an ``arbitrary`` (serialized) k-block reduction axis and
online-softmax (m, l) carries in VMEM scratch.  At short sequence
lengths that machinery IS the cost — the r5 profile measured 10.2 TF/s
fwd at s=1024 causal (~5% of v5e peak) vs 45-50 TF/s at s=4096-8192,
because each grid step does a tiny dot and the correction multiplies /
scratch round-trips dominate.  The reference ships per-seqlen
{128,256,384,512} SM80 kernels for exactly this reason
(apex/contrib/csrc/fmha/, setup.py:405-415).

This module is the TPU analog of that seqlen-specialized family, as ONE
kernel pair instead of four: when the whole kv sequence fits a single
k-block, compute the exact softmax in one pass —

- **no online softmax**: no (m, l) scratch, no correction multiplies,
  no ``arbitrary`` grid axis; every grid dimension is ``parallel``;
- **bh packing**: the grid is 1-D over blocked ``batch*heads``; each
  program holds ``block_bh`` heads' q/k/v resident in VMEM and issues
  their dots back-to-back from one unrolled body, so the MXU pipeline
  stays full instead of draining between b*h tiny programs;
- **one fused backward**: a single kernel emits dq, dk, dv (and dbias)
  in one pass, reading q/k/v/do once and computing the score replay
  (s, p, dp, dz) once — the flash split (dkv + dq kernels) exists only
  to bound residency across k/q block loops, which a short sequence
  does not have.

Feature parity with the flash kernel is total: additive bias (all
broadcast batchings) with a real bias gradient, segment-id varlen
masking, and counter-based dropout replayed from the SAME hash
(``attention._keep_mask``), so for a given seed the flash kernel, this
kernel, and the XLA reference drop bit-identical entries.

Dispatch: ``flash_attention(implementation=None)`` auto-routes here
below the measured crossover (``FMHA_SHORT_MAX_SEQ``, overridable via
``APEX_TPU_FMHA_SHORT_MAX_SEQ``); ``implementation="short"`` forces
this kernel (strict — lowering failures raise).  The crossover default
is provisional until the next TPU capture: ``tools/kernel_validation.py``
sweeps s∈{128,256,384,512,1024} for short-vs-flash-vs-XLA and records
the measured boundary into KERNELS_TPU.json.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.attention import (
    _LANES,
    _NEG_INF,
    _interpret,
    _keep_mask,
    _keep_threshold,
    _pad_seq,
    _prec,
    mha_reference,
)
from apex_tpu.ops.common import shape_struct
from apex_tpu.utils.platform import default_implementation

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None

__all__ = ["fmha_short", "FMHA_SHORT_MAX_SEQ", "short_seq_threshold"]

#: Auto-dispatch crossover: ``flash_attention`` routes to this kernel
#: when both sq and sk are at or below this bound.  512 matches the
#: reference's fmhalib window ({128,256,384,512}) and keeps the fused
#: backward's score-space temporaries comfortably inside Mosaic's 16 MB
#: scoped-vmem budget at every block_bh the auto-sizer picks.  The value
#: is PROVISIONAL until the next TPU window: tools/kernel_validation.py
#: measures short-vs-flash at s∈{128,256,384,512,1024} and the capture
#: gates on this constant agreeing with the measurement (the same
#: record-don't-hand-pick contract as FLASH_FP32_XLA_MAX_SEQ).
FMHA_SHORT_MAX_SEQ = 512

#: Per-program score-space budget (elements): block_bh is sized so
#: block_bh * sq_p * sk_p stays at or under this.  512*1024 is the same
#: area bound the fp32 flash blocks are clamped to
#: (attention.FLASH_FP32_MAX_BLOCK_AREA) — the fused backward keeps ~4
#: (sq, sk) fp32 temporaries live per unrolled head, so this keeps the
#: worst case near the flash backward's proven-compiling footprint.
FMHA_SHORT_BLOCK_ELEMS = 512 * 1024

#: Unroll bound: the bh block is an unrolled python loop of 2-D MXU
#: dots (the guaranteed Mosaic lowering path — batched 3-D dots are
#: not); 16 copies of the body bounds code size while still amortizing
#: grid-step overhead 16x at s=128.
FMHA_SHORT_MAX_BLOCK_BH = 16


def short_seq_threshold() -> int:
    """The auto-dispatch crossover, env-overridable so an ops rollout
    can move the boundary without a code change
    (``APEX_TPU_FMHA_SHORT_MAX_SEQ=0`` disables short dispatch)."""
    v = os.environ.get("APEX_TPU_FMHA_SHORT_MAX_SEQ")
    return int(v) if v else FMHA_SHORT_MAX_SEQ


def default_block_bh(sq_p: int, sk_p: int, bh: int) -> int:
    """How many (batch*head) programs one grid step packs."""
    by_area = max(1, FMHA_SHORT_BLOCK_ELEMS // (sq_p * sk_p))
    return max(1, min(by_area, FMHA_SHORT_MAX_BLOCK_BH, bh))


class _ShortConfig(NamedTuple):
    """Static kernel configuration (hashable for custom_vjp)."""

    sm_scale: float
    causal: bool
    dropout_rate: float
    block_bh: int
    q_len: int       # unpadded
    kv_len: int      # unpadded
    heads: int       # heads per batch entry (per-batch bias index map)
    # "shared": one (1, sq, sk) bias block for every program;
    # "per_batch": (b, sq, sk), one block per batch entry — block_bh is
    #   then constrained to divide heads so each program's bh block
    #   stays inside a single batch (no h-times broadcast in HBM);
    # "per_head": (bh_p, sq, sk), one row per (batch*head)
    bias_mode: str
    bias_grad: bool
    hi_precision: bool = False


def _dot2(a, b, contract, cfg):
    return jax.lax.dot_general(
        a, b, (contract, ((), ())),
        preferred_element_type=jnp.float32,
        precision=_prec(cfg),
    )


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _short_fwd_kernel(*refs, cfg: _ShortConfig, has_bias, has_segs,
                      has_dropout):
    (q_ref, k_ref, v_ref), rest = refs[:3], refs[3:]
    bias_ref = qseg_ref = kseg_ref = seed_ref = None
    if has_bias:
        bias_ref, rest = rest[0], rest[1:]
    if has_segs:
        (qseg_ref, kseg_ref), rest = rest[:2], rest[2:]
    if has_dropout:
        seed_ref, rest = rest[0], rest[1:]
    o_ref, lse_ref = rest

    i = pl.program_id(0)
    sq_p, sk_p = q_ref.shape[1], k_ref.shape[1]
    # q padding needs no forward mask (padded rows are sliced off by the
    # caller and replayed under an explicit q-row mask in the backward)
    needs_mask = cfg.causal or has_segs or cfg.kv_len < sk_p
    if needs_mask or has_dropout:
        q_idx = jax.lax.broadcasted_iota(jnp.int32, (sq_p, sk_p), 0)
        k_idx = jax.lax.broadcasted_iota(jnp.int32, (sq_p, sk_p), 1)
    base_mask = None
    if needs_mask:
        base_mask = k_idx < cfg.kv_len
        if cfg.causal:
            base_mask = jnp.logical_and(base_mask, k_idx <= q_idx)

    for bi in range(cfg.block_bh):
        q = q_ref[bi].astype(jnp.float32) * cfg.sm_scale    # (sq_p, d)
        s = _dot2(q, k_ref[bi].astype(jnp.float32),
                  ((1,), (1,)), cfg)                        # (sq_p, sk_p)
        if has_bias:
            # shared/per_batch blocks carry one (sq, sk) slab for the
            # whole program; per_head carries one per bi
            s = s + bias_ref[
                bi if cfg.bias_mode == "per_head" else 0
            ].astype(jnp.float32)
        mask = base_mask
        if has_segs:
            seg = qseg_ref[bi, 0][:, None] == kseg_ref[bi, 0][None, :]
            mask = seg if mask is None else jnp.logical_and(mask, seg)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        l = jnp.sum(p, axis=-1, keepdims=True)
        if has_dropout:
            keep = _keep_mask(
                seed_ref[0, 0], i * cfg.block_bh + bi, q_idx, k_idx,
                jnp.uint32(_keep_threshold(cfg.dropout_rate)),
            )
            p_v = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - cfg.dropout_rate))
        else:
            p_v = p
        acc = _dot2(p_v, v_ref[bi].astype(jnp.float32), ((1,), (0,)), cfg)
        l = jnp.maximum(l, 1e-30)
        o_ref[bi] = (acc / l).astype(o_ref.dtype)
        lse_ref[bi, 0] = m[:, 0] + jnp.log(l[:, 0])


# ---------------------------------------------------------------------------
# Fused backward kernel (dq + dk + dv + optional dbias in one pass)
# ---------------------------------------------------------------------------


def _short_bwd_kernel(*refs, cfg: _ShortConfig, has_bias, has_segs,
                      has_dropout):
    (q_ref, k_ref, v_ref), rest = refs[:3], refs[3:]
    bias_ref = qseg_ref = kseg_ref = seed_ref = None
    if has_bias:
        bias_ref, rest = rest[0], rest[1:]
    if has_segs:
        (qseg_ref, kseg_ref), rest = rest[:2], rest[2:]
    if has_dropout:
        seed_ref, rest = rest[0], rest[1:]
    do_ref, lse_ref, delta_ref = rest[:3]
    rest = rest[3:]
    emit_dbias = has_bias and cfg.bias_grad
    if emit_dbias:
        dq_ref, dk_ref, dv_ref, dbias_ref = rest
    else:
        (dq_ref, dk_ref, dv_ref), dbias_ref = rest, None

    i = pl.program_id(0)
    sq_p, sk_p = q_ref.shape[1], k_ref.shape[1]
    # unlike the forward, padded q ROWS must be masked here: their lse
    # is garbage (fully-masked rows clamp l), and dk/dv sum over sq
    needs_mask = (cfg.causal or has_segs or cfg.kv_len < sk_p
                  or cfg.q_len < sq_p)
    if needs_mask or has_dropout:
        q_idx = jax.lax.broadcasted_iota(jnp.int32, (sq_p, sk_p), 0)
        k_idx = jax.lax.broadcasted_iota(jnp.int32, (sq_p, sk_p), 1)
    base_mask = None
    if needs_mask:
        base_mask = jnp.logical_and(q_idx < cfg.q_len, k_idx < cfg.kv_len)
        if cfg.causal:
            base_mask = jnp.logical_and(base_mask, k_idx <= q_idx)

    db_acc = None
    for bi in range(cfg.block_bh):
        qblk = q_ref[bi].astype(jnp.float32)               # (sq_p, d)
        kblk = k_ref[bi].astype(jnp.float32)               # (sk_p, d)
        vblk = v_ref[bi].astype(jnp.float32)
        doblk = do_ref[bi].astype(jnp.float32)
        lse = lse_ref[bi, 0][:, None]                      # (sq_p, 1)
        delta = delta_ref[bi, 0][:, None]
        s = _dot2(qblk, kblk, ((1,), (1,)), cfg) * cfg.sm_scale
        if has_bias:
            s = s + bias_ref[
                bi if cfg.bias_mode == "per_head" else 0
            ].astype(jnp.float32)
        mask = base_mask
        if has_segs:
            seg = qseg_ref[bi, 0][:, None] == kseg_ref[bi, 0][None, :]
            mask = seg if mask is None else jnp.logical_and(mask, seg)
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = _dot2(doblk, vblk, ((1,), (1,)), cfg)         # (sq_p, sk_p)
        if has_dropout:
            keep = _keep_mask(
                seed_ref[0, 0], i * cfg.block_bh + bi, q_idx, k_idx,
                jnp.uint32(_keep_threshold(cfg.dropout_rate)),
            )
            inv_kp = 1.0 / (1.0 - cfg.dropout_rate)
            p_drop = jnp.where(keep, p, 0.0) * inv_kp
            dp = jnp.where(keep, dp, 0.0) * inv_kp
        else:
            p_drop = p
        dv_ref[bi] = _dot2(p_drop, doblk, ((0,), (0,)), cfg).astype(
            dv_ref.dtype)
        dz = p * (dp - delta)                              # grad wrt s+bias
        if emit_dbias:
            if cfg.bias_mode == "per_head":
                dbias_ref[bi] = dz.astype(dbias_ref.dtype)
            else:
                # shared/per_batch: one partial sum per program; the
                # vjp folds the program axis back in XLA
                db_acc = dz if db_acc is None else db_acc + dz
        dk_ref[bi] = _dot2(dz * cfg.sm_scale, qblk, ((0,), (0,)),
                           cfg).astype(dk_ref.dtype)
        dq_ref[bi] = _dot2(dz * cfg.sm_scale, kblk, ((1,), (0,)),
                           cfg).astype(dq_ref.dtype)
    if emit_dbias and cfg.bias_mode != "per_head":
        dbias_ref[0] = db_acc.astype(dbias_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


def _in_specs(cfg, sq_p, sk_p, d_p, has_bias, has_segs, has_dropout):
    bb = cfg.block_bh
    specs = [
        pl.BlockSpec((bb, sq_p, d_p), lambda i: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bb, sk_p, d_p), lambda i: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bb, sk_p, d_p), lambda i: (i, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    if has_bias:
        if cfg.bias_mode == "per_head":
            specs.append(pl.BlockSpec((bb, sq_p, sk_p),
                                      lambda i: (i, 0, 0),
                                      memory_space=pltpu.VMEM))
        elif cfg.bias_mode == "per_batch":
            # block_bh divides heads (wrapper invariant), so program i
            # covers bh rows of exactly one batch entry: (i*bb)//heads
            heads = cfg.heads
            specs.append(pl.BlockSpec(
                (1, sq_p, sk_p), lambda i: ((i * bb) // heads, 0, 0),
                memory_space=pltpu.VMEM))
        else:
            specs.append(pl.BlockSpec((1, sq_p, sk_p),
                                      lambda i: (0, 0, 0),
                                      memory_space=pltpu.VMEM))
    if has_segs:
        # (bh, 1, s): the middle singleton keeps the trailing two block
        # dims Mosaic-tileable, same trick as the flash kernel
        specs.append(pl.BlockSpec((bb, 1, sq_p), lambda i: (i, 0, 0)))
        specs.append(pl.BlockSpec((bb, 1, sk_p), lambda i: (i, 0, 0)))
    if has_dropout:
        specs.append(pl.BlockSpec((1, 1), lambda i: (0, 0),
                                  memory_space=pltpu.SMEM))
    return specs


def _compiler_params():
    from apex_tpu.ops.common import tpu_compiler_params

    # every axis parallel: no serialized reduction dimension exists
    return tpu_compiler_params(dimension_semantics=("parallel",))


def _short_fwd_pallas(q, k, v, bias, qseg, kseg, seed, cfg: _ShortConfig):
    bh_p, sq_p, d_p = q.shape
    sk_p = k.shape[1]
    has_bias = bias is not None
    has_segs = qseg is not None
    has_dropout = cfg.dropout_rate > 0.0
    inputs = [q, k, v]
    if has_bias:
        inputs.append(bias)
    if has_segs:
        inputs.extend([qseg, kseg])
    if has_dropout:
        inputs.append(seed)
    out, lse = pl.pallas_call(
        functools.partial(
            _short_fwd_kernel, cfg=cfg, has_bias=has_bias,
            has_segs=has_segs, has_dropout=has_dropout,
        ),
        grid=(bh_p // cfg.block_bh,),
        in_specs=_in_specs(cfg, sq_p, sk_p, d_p, has_bias, has_segs,
                           has_dropout),
        out_specs=[
            pl.BlockSpec((cfg.block_bh, sq_p, d_p), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cfg.block_bh, 1, sq_p), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            shape_struct((bh_p, sq_p, d_p), q.dtype, q, k, v),
            shape_struct((bh_p, 1, sq_p), jnp.float32, q, k, v),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*inputs)
    return out, lse


def _short_bwd_pallas(q, k, v, bias, qseg, kseg, seed, out, lse, do,
                      cfg: _ShortConfig):
    bh_p, sq_p, d_p = q.shape
    sk_p = k.shape[1]
    has_bias = bias is not None
    has_segs = qseg is not None
    has_dropout = cfg.dropout_rate > 0.0
    emit_dbias = has_bias and cfg.bias_grad
    # delta = rowsum(do * o) — cheap, XLA fuses it
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )[:, None, :]

    inputs = [q, k, v]
    if has_bias:
        inputs.append(bias)
    if has_segs:
        inputs.extend([qseg, kseg])
    if has_dropout:
        inputs.append(seed)
    inputs.extend([do, lse, delta])

    in_specs = _in_specs(cfg, sq_p, sk_p, d_p, has_bias, has_segs,
                         has_dropout)
    in_specs.extend([
        pl.BlockSpec((cfg.block_bh, sq_p, d_p), lambda i: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((cfg.block_bh, 1, sq_p), lambda i: (i, 0, 0)),
        pl.BlockSpec((cfg.block_bh, 1, sq_p), lambda i: (i, 0, 0)),
    ])
    out_specs = [
        pl.BlockSpec((cfg.block_bh, sq_p, d_p), lambda i: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((cfg.block_bh, sk_p, d_p), lambda i: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((cfg.block_bh, sk_p, d_p), lambda i: (i, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        shape_struct((bh_p, sq_p, d_p), q.dtype, q, k, v, do),
        shape_struct((bh_p, sk_p, d_p), k.dtype, q, k, v, do),
        shape_struct((bh_p, sk_p, d_p), v.dtype, q, k, v, do),
    ]
    if emit_dbias:
        if cfg.bias_mode == "per_head":
            out_specs.append(pl.BlockSpec(
                (cfg.block_bh, sq_p, sk_p), lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM))
            out_shape.append(
                shape_struct((bh_p, sq_p, sk_p), jnp.float32, q, k, v, do))
        else:
            # shared/per_batch: per-PROGRAM partial sums — "parallel"
            # grid steps cannot accumulate into one shared block, so
            # each program writes its bh-block's sum and the vjp folds
            # the grid axis in XLA
            n_prog = bh_p // cfg.block_bh
            out_specs.append(pl.BlockSpec(
                (1, sq_p, sk_p), lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM))
            out_shape.append(
                shape_struct((n_prog, sq_p, sk_p), jnp.float32,
                             q, k, v, do))
    res = pl.pallas_call(
        functools.partial(
            _short_bwd_kernel, cfg=cfg, has_bias=has_bias,
            has_segs=has_segs, has_dropout=has_dropout,
        ),
        grid=(bh_p // cfg.block_bh,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*inputs)
    if emit_dbias:
        dq, dk, dv, dbias = res
    else:
        (dq, dk, dv), dbias = res, None
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# custom_vjp wrapper (flattened, padded (bh_p, s_p, d_p) layout)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def _short(q, k, v, bias, qseg, kseg, seed, cfg):
    out, _ = _short_fwd_pallas(q, k, v, bias, qseg, kseg, seed, cfg)
    return out


def _short_fwd(q, k, v, bias, qseg, kseg, seed, cfg):
    out, lse = _short_fwd_pallas(q, k, v, bias, qseg, kseg, seed, cfg)
    return out, (q, k, v, bias, qseg, kseg, seed, out, lse)


def _int_zero(x):
    return (
        None if x is None
        else np.zeros(x.shape, jax.dtypes.float0)
    )


def _short_bwd(cfg, res, do):
    q, k, v, bias, qseg, kseg, seed, out, lse = res
    dq, dk, dv, dbias = _short_bwd_pallas(
        q, k, v, bias, qseg, kseg, seed, out, lse, do, cfg
    )
    if bias is not None and not cfg.bias_grad:
        # constant-mask contract: caller declared the bias non-trainable
        dbias = jnp.zeros_like(bias)
    elif bias is not None:
        if cfg.bias_mode == "shared":
            # fold the per-program partial sums back to the one shared
            # (1, sq, sk) bias block the primal consumed
            dbias = jnp.sum(dbias, axis=0, keepdims=True)
        elif cfg.bias_mode == "per_batch":
            # (n_prog, sq, sk) partial sums, heads//block_bh programs
            # per batch entry → (b, sq, sk), the primal's bias shape
            n_prog, psq, psk = dbias.shape
            per_batch = cfg.heads // cfg.block_bh
            dbias = dbias.reshape(
                n_prog // per_batch, per_batch, psq, psk).sum(axis=1)
        dbias = dbias.astype(bias.dtype)
        # per-head bias needs no fold: the kernel input was already
        # (bh_p, sq, sk), and the wrapper's broadcast_to transpose
        # sums heads/batches back to the user's bias shape
    return (dq, dk, dv, dbias, _int_zero(qseg), _int_zero(kseg),
            _int_zero(seed))


_short.defvjp(_short_fwd, _short_bwd)


# ---------------------------------------------------------------------------
# Public entry point
# ---------------------------------------------------------------------------


def fmha_short(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
    q_segment_ids: Optional[jnp.ndarray] = None,
    kv_segment_ids: Optional[jnp.ndarray] = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
    bias_requires_grad: bool = True,
    block_bh: Optional[int] = None,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """Single-pass short-sequence attention over ``(b, h, s, d)``.

    Same contract as :func:`~apex_tpu.ops.attention.flash_attention`
    (bias / segment ids / counter-hash dropout, identical masks for a
    given seed), specialized for sequences where the whole kv fits one
    block.  ``block_bh`` overrides how many (batch*head) programs one
    grid step packs (default: sized by ``FMHA_SHORT_BLOCK_ELEMS``).

    Most callers should not call this directly: ``flash_attention``
    auto-routes here below the measured crossover, and accepts
    ``implementation="short"`` to force this kernel.
    """
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("segment ids must be given for both q and kv")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if bias is not None and bias.ndim < 4:
        bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
    from apex_tpu.ops.common import KernelLoweringError, run_kernel

    if implementation == "short":
        # the flash_attention-facing spelling: forcing "short" on the
        # short entry point itself means the strict kernel path (NOT a
        # silent XLA resolve, which run_kernel would otherwise do for
        # any non-"pallas" string)
        implementation = "pallas"
    if implementation not in (None, "pallas", "xla"):
        raise ValueError(
            f"unknown implementation {implementation!r}; expected None, "
            "'pallas'/'short', or 'xla'"
        )
    if pl is None and implementation == "pallas":
        raise KernelLoweringError(
            "implementation='pallas' requested but Pallas failed to import"
        )
    impl = implementation or default_implementation()
    if pl is None:
        impl = "xla"

    def _xla_path():
        return mha_reference(
            q, k, v, causal=causal, sm_scale=sm_scale, bias=bias,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )

    def _pallas_path():
        return _fmha_short_pallas(
            q, k, v, causal, sm_scale, bias, q_segment_ids,
            kv_segment_ids, dropout_rate, dropout_seed,
            bias_requires_grad, block_bh,
        )

    return run_kernel(
        "fmha_short", _pallas_path, _xla_path, implementation, impl
    )


def _fmha_short_pallas(
    q, k, v, causal, sm_scale, bias, q_segment_ids, kv_segment_ids,
    dropout_rate, dropout_seed, bias_requires_grad, block_bh,
):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = (1.0 / d**0.5) if sm_scale is None else float(sm_scale)
    # pad every in-kernel dimension to the 128-lane tile: seq lengths
    # become both sublane (scores) and lane (lse) extents, and zero
    # k/v columns do not change q@k^T
    pad_q = (-sq) % _LANES
    pad_k = (-sk) % _LANES
    pad_d = (-d) % _LANES
    sq_p, sk_p, d_p = sq + pad_q, sk + pad_k, d + pad_d
    if pad_d:
        padd = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        q, k, v = padd(q), padd(k), padd(v)

    bh = b * h
    if block_bh is None:
        bb = default_block_bh(sq_p, sk_p, bh)
    else:
        bb = max(1, min(int(block_bh), bh))
    bias_mode = "shared"
    if bias is not None and bias.shape[0] > 1 and bias.shape[1] == 1:
        # per-batch bias rides its native (b, sq, sk) layout; each
        # program must then stay inside one batch entry, so clamp
        # block_bh to a divisor of heads (heads are small powers of
        # two in practice — the clamp rarely bites)
        bias_mode = "per_batch"
        while h % bb:
            bb -= 1
    pad_bh = (-bh) % bb
    bh_p = bh + pad_bh

    def flat(x, pad_s):
        x = _pad_seq(x.reshape(bh, x.shape[2], x.shape[3]), pad_s)
        return jnp.pad(x, ((0, pad_bh), (0, 0), (0, 0))) if pad_bh else x

    qf, kf, vf = flat(q, pad_q), flat(k, pad_k), flat(v, pad_k)

    bias_flat = None
    if bias is not None:
        if bias_mode == "per_batch":
            bias_flat = jnp.broadcast_to(
                bias, (b, 1, sq, sk)).reshape(b, sq, sk)
        elif bias.shape[0] == 1 and bias.shape[1] == 1:
            bias_flat = jnp.broadcast_to(
                bias, (1, 1, sq, sk)).reshape(1, sq, sk)
        else:
            bias_mode = "per_head"
            bias_flat = jnp.broadcast_to(
                bias, (b, h, sq, sk)).reshape(bh, sq, sk)
        bias_flat = _pad_seq(_pad_seq(bias_flat, pad_q, axis=1),
                             pad_k, axis=2)
        if bias_mode == "per_head" and pad_bh:
            bias_flat = jnp.pad(bias_flat, ((0, pad_bh), (0, 0), (0, 0)))

    qseg = kseg = None
    if q_segment_ids is not None:
        # per-bh segment rows keep the 1-D grid's index maps trivial;
        # padded q rows keep id 0 (flash convention — their lse stays
        # finite), padded kv ids get -1 so they never match a real
        # segment
        def seg_flat(ids, pad_s, pad_value):
            ids = jnp.broadcast_to(
                ids.astype(jnp.int32)[:, None, None, :],
                (b, h, 1, ids.shape[1]),
            ).reshape(bh, 1, ids.shape[1])
            if pad_s:
                ids = jnp.pad(ids, ((0, 0), (0, 0), (0, pad_s)),
                              constant_values=pad_value)
            if pad_bh:
                ids = jnp.pad(ids, ((0, pad_bh), (0, 0), (0, 0)),
                              constant_values=pad_value)
            return ids

        qseg = seg_flat(q_segment_ids, pad_q, 0)
        kseg = seg_flat(kv_segment_ids, pad_k, -1)

    seed_arr = None
    if dropout_rate > 0.0:
        seed_arr = jnp.asarray(dropout_seed, jnp.uint32).reshape(1, 1)

    cfg = _ShortConfig(
        sm_scale=scale, causal=causal, dropout_rate=float(dropout_rate),
        block_bh=bb, q_len=sq, kv_len=sk, heads=h, bias_mode=bias_mode,
        bias_grad=bool(bias_requires_grad),
        hi_precision=(q.dtype == jnp.float32),
    )
    out = _short(qf, kf, vf, bias_flat, qseg, kseg, seed_arr, cfg)
    out = out[:bh, :sq].reshape(b, h, sq, d_p)
    if pad_d:
        out = out[..., :d]
    return out

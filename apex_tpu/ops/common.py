"""Shared helpers for the Pallas kernels."""

from __future__ import annotations

import jax

__all__ = ["shape_struct"]


def shape_struct(shape, dtype, *varying_like) -> jax.ShapeDtypeStruct:
    """A ``ShapeDtypeStruct`` whose ``vma`` (varying-across-mesh axes) is
    the union of the given operands' — required so ``pallas_call`` results
    type-check under ``shard_map(check_vma=True)``, e.g. when a kernel
    runs on dp-sharded activations inside a tensor-parallel region."""
    try:
        sets = [jax.typeof(x).vma for x in varying_like]
        vma = frozenset().union(*sets) if sets else frozenset()
    except Exception:
        vma = None
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)

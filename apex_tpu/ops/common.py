"""Shared helpers for the Pallas kernels."""

from __future__ import annotations

import logging
import os

import jax

__all__ = [
    "shape_struct", "run_kernel", "KernelLoweringError",
    "tpu_compiler_params",
]


def tpu_compiler_params(**kwargs):
    """Version-portable ``pltpu.CompilerParams`` (renamed from
    ``TPUCompilerParams`` across jax releases; 0.4.x ships the old
    name)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)

_logger = logging.getLogger("apex_tpu")


class KernelLoweringError(RuntimeError):
    """A Pallas kernel failed to trace/lower on a path where falling back
    silently is not allowed (explicit ``implementation='pallas'`` or
    ``APEX_TPU_STRICT_KERNELS=1``)."""


def run_kernel(name, pallas_fn, xla_fn, requested_impl, resolved_impl):
    """Dispatch between a Pallas kernel and its XLA fallback.

    Fallback policy (the assertable contract the reference gets from its
    import-time extension probing, apex/parallel/distributed.py:13-23):

    - ``requested_impl == "pallas"``: the user asked for the kernel —
      a lowering failure RAISES ``KernelLoweringError`` instead of
      silently degrading.
    - auto mode (``requested_impl is None``): a failure falls back to
      XLA with a logged warning, unless ``APEX_TPU_STRICT_KERNELS=1``
      makes every fallback an error (CI smoke mode).
    """
    if resolved_impl != "pallas":
        return xla_fn()
    strict = (
        requested_impl == "pallas"
        or bool(os.environ.get("APEX_TPU_STRICT_KERNELS"))
    )
    try:
        return pallas_fn()
    except Exception as e:  # trace-time shape/lowering rejection
        if strict:
            raise KernelLoweringError(
                f"pallas kernel {name!r} failed to lower and strict mode "
                f"is on (explicit implementation='pallas' or "
                f"APEX_TPU_STRICT_KERNELS=1): {e}"
            ) from e
        _logger.warning(
            "pallas kernel %s unavailable (%s); falling back to XLA",
            name, e,
        )
        return xla_fn()


def shape_struct(shape, dtype, *varying_like) -> jax.ShapeDtypeStruct:
    """A ``ShapeDtypeStruct`` whose ``vma`` (varying-across-mesh axes) is
    the union of the given operands' — required so ``pallas_call`` results
    type-check under ``shard_map(check_vma=True)``, e.g. when a kernel
    runs on dp-sharded activations inside a tensor-parallel region."""
    try:
        sets = [jax.typeof(x).vma for x in varying_like]
        vma = frozenset().union(*sets) if sets else frozenset()
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except Exception:
        # jax without typeof().vma / the ShapeDtypeStruct vma kwarg:
        # plain struct (check_vma shard_map is unavailable there anyway)
        return jax.ShapeDtypeStruct(shape, dtype)

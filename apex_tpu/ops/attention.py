"""Flash attention for TPU (Pallas), plus a reference XLA path.

Capability match — and supersession — of the reference's attention stack:
``fmhalib`` (apex/contrib/csrc/fmha/, fp16, seqlen<=512, SM80-only) and the
eight ``fast_*_multihead_attn`` extensions
(apex/contrib/csrc/multihead_attn/).  Those kernels materialise the
(sq, sk) score matrix per head; flash attention never does, so the TPU
design has no seqlen window: one online-softmax kernel covers every
sequence length, causal or not, bf16-first.  Beyond the reference's
kernels this one also supports, *in kernel*:

- **segment ids** (varlen): the TPU-native form of the reference's
  ``cu_seqlens`` packed-batch API (apex/contrib/fmha/fmha.py:33-80) —
  tokens attend only within equal segment ids;
- **additive bias** with a real bias gradient;
- **probability dropout** replayed exactly in the backward pass from a
  counter-based hash (the role Philox plays in the reference,
  apex/contrib/csrc/multihead_attn/philox.h) — the same hash evaluates
  in plain XLA, so the reference path produces bit-identical masks and
  the two implementations stay directly comparable.

Layout: ``(batch, heads, seq, head_dim)``.  Softmax statistics are fp32;
the accumulator is fp32; output matches the input dtype.

Kernel strategy (chosen for VMEM residency, see pallas_guide): all three
kernels run a 3-D grid with the reduction dimension innermost and carry
running state in VMEM scratch, so **no kernel ever holds a whole
sequence of K/V** — per-program residency is O(block_q·d + block_k·d)
and long sequences (32k+) compile:

- forward: grid ``(batch*heads, q_blocks, k_blocks)``; online-softmax
  (m, l, acc) scratch accumulates across the k-block dimension.
- backward dK/dV: grid ``(batch*heads, k_blocks, q_blocks)``; dK/dV
  scratch accumulates across the q-block dimension.
- backward dQ (+dBias): grid ``(batch*heads, q_blocks, k_blocks)``;
  dQ scratch accumulates across the k-block dimension.  Scores are
  replayed from the saved log-sum-exp (flash-attention-2 split).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.common import shape_struct
from apex_tpu.utils.platform import default_implementation, is_tpu

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None

__all__ = ["flash_attention", "mha_reference"]

_NEG_INF = -1e30
_LANES = 128


# ---------------------------------------------------------------------------
# Counter-based dropout hash (shared by the Pallas kernels and the XLA
# reference so both paths draw the *same* mask for a given seed)
# ---------------------------------------------------------------------------


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    """32-bit finalizer (lowrey/murmur-style avalanche), uint32 in/out."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _keep_mask(seed, bh, q_idx, k_idx, keep_threshold):
    """Deterministic keep mask for dropout.

    ``seed``: uint32 scalar; ``bh``: flattened batch*head index (scalar or
    array); ``q_idx``/``k_idx``: broadcastable int32 position arrays;
    ``keep_threshold``: uint32 in [0, 2^24] = keep_prob * 2^24.
    """
    seed = seed.astype(jnp.uint32)
    bh = jnp.asarray(bh).astype(jnp.uint32)
    h = _mix32(seed ^ (bh * jnp.uint32(0x9E3779B1)))
    r = _mix32(
        (h + q_idx.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
        ^ (k_idx.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D))
    )
    return (r >> 8) < keep_threshold


def _keep_threshold(dropout_rate: float) -> int:
    return int(round((1.0 - dropout_rate) * (1 << 24)))


# ---------------------------------------------------------------------------
# XLA reference path
# ---------------------------------------------------------------------------


def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
    q_segment_ids: Optional[jnp.ndarray] = None,
    kv_segment_ids: Optional[jnp.ndarray] = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
) -> jnp.ndarray:
    """Plain XLA attention with fp32 softmax — the correctness reference,
    playing the role of the reference's pure-PyTorch ``impl='default'``
    path (apex/contrib/multihead_attn/self_multihead_attn_func.py).

    Dropout uses the same counter-based hash as the Pallas kernel, so for
    a given ``dropout_seed`` both implementations drop the same entries.
    """
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("segment ids must be given for both q and kv")
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = (1.0 / d**0.5) if sm_scale is None else sm_scale
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    mask = jnp.ones((1, 1, sq, sk), bool)
    if causal:
        q_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        mask = mask & (k_idx <= q_idx)[None, None]
    if q_segment_ids is not None:
        mask = mask & (
            q_segment_ids[:, None, :, None] == kv_segment_ids[:, None, None, :]
        )
    s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.broadcast_to(mask, p.shape), p, 0.0)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        seed = jnp.asarray(dropout_seed, jnp.uint32)
        bh_idx = jnp.arange(b * h, dtype=jnp.int32).reshape(b, h, 1, 1)
        q_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)[None, None]
        k_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)[None, None]
        keep = _keep_mask(seed, bh_idx, q_idx, k_idx,
                          jnp.uint32(_keep_threshold(dropout_rate)))
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


def _interpret() -> bool:
    """Interpreter-mode Pallas off-TPU: the kernel bodies still run (and
    are testable) on CPU, at interpreter speed."""
    return not is_tpu()


class _FAConfig(NamedTuple):
    """Static kernel configuration (hashable for custom_vjp)."""

    sm_scale: float
    causal: bool
    dropout_rate: float
    block_q: int
    block_k: int
    q_len: int       # unpadded
    kv_len: int      # unpadded
    heads: int       # heads per batch entry (for segment-id index maps)
    # flattened-bias batching: 0 = no bias, 1 = one (sq, sk) bias shared by
    # all programs, BIAS_PER_BATCH = one per batch entry (b, sq, sk),
    # BIAS_PER_HEAD = one per program (b*h, sq, sk)
    bias_batch: int
    # whether the backward pass materialises dbias (False for constant
    # masks keeps the causal block-skip and avoids a (b*h, sq, sk) buffer)
    bias_grad: bool
    # full-precision MXU passes for the in-kernel dots: set for fp32
    # inputs, where the default (single bf16 pass) loses ~3 decimal
    # digits vs the XLA path at long sequence lengths (KERNELS_TPU gate)
    hi_precision: bool = False


BIAS_PER_BATCH = -2
BIAS_PER_HEAD = -1

#: fp32 auto mode routes to XLA at or below this sequence length
#: (measured crossover, KERNELS_TPU.json; also read by
#: tools/kernel_validation.py so the recorded auto_impl cannot drift
#: from the actual dispatch)
FLASH_FP32_XLA_MAX_SEQ = 1024


def _prec(cfg):
    return jax.lax.Precision.HIGHEST if cfg.hi_precision else None


# ---------------------------------------------------------------------------
# Pallas forward
# ---------------------------------------------------------------------------


def _fa_fwd_kernel(
    *refs, cfg: _FAConfig, num_k: int, has_bias, has_segs, has_dropout,
):
    (q_ref, k_ref, v_ref), rest = refs[:3], refs[3:]
    bias_ref = qseg_ref = kseg_ref = seed_ref = None
    if has_bias:
        bias_ref, rest = rest[0], rest[1:]
    if has_segs:
        (qseg_ref, kseg_ref), rest = rest[:2], rest[2:]
    if has_dropout:
        seed_ref, rest = rest[0], rest[1:]
    o_ref, lse_ref, acc_ref, m_ref, l_ref = rest

    i, j, kb = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    block_q, block_k = cfg.block_q, cfg.block_k
    if cfg.causal:
        last_kb = jnp.minimum(
            num_k - 1, ((j + 1) * block_q - 1) // block_k
        )
    else:
        last_kb = num_k - 1

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _body(masked):
        q = q_ref[0].astype(jnp.float32) * cfg.sm_scale    # (block_q, d)
        kblk = k_ref[0].astype(jnp.float32)                # (block_k, d)
        vblk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(cfg),
        )                                                  # (block_q, block_k)
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if masked or has_dropout:
            q_global = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            k_global = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
        if masked:
            mask = k_global < cfg.kv_len
            if cfg.causal:
                mask = jnp.logical_and(mask, k_global <= q_global)
            if has_segs:
                mask = jnp.logical_and(
                    mask, qseg_ref[0, 0][:, None] == kseg_ref[0, 0][None, :]
                )
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if masked:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        if has_dropout:
            keep = _keep_mask(
                seed_ref[0, 0], i, q_global, k_global,
                jnp.uint32(_keep_threshold(cfg.dropout_rate)),
            )
            p_acc = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - cfg.dropout_rate))
        else:
            p_acc = p
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p_acc, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(cfg),
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    conds = []
    if cfg.causal:
        conds.append(kb * block_k + (block_k - 1) > j * block_q)
    if cfg.kv_len < num_k * block_k:                        # kv padding
        conds.append(kb == num_k - 1)
    _mask_specialized(kb <= last_kb, conds, has_segs, _body)

    @pl.when(kb == last_kb)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:, 0] + jnp.log(l[:, 0])


def _fwd_in_specs(cfg, d, psq, psk, has_bias, has_segs, has_dropout,
                  swap_grid=False):
    """Input BlockSpecs shared by forward and dq kernels.

    ``swap_grid``: dkv kernel uses grid (i, kb, jq); forward/dq use
    (i, jq, kb).  Index maps below are written for (i, jq, kb) and
    wrapped when swapped.
    """
    block_q, block_k, heads = cfg.block_q, cfg.block_k, cfg.heads

    def w(f):  # rewire grid axes for the dkv kernel
        if not swap_grid:
            return f
        return lambda i, kb, jq: f(i, jq, kb)

    specs = [
        pl.BlockSpec((1, block_q, d), w(lambda i, j, kb: (i, j, 0)),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), w(lambda i, j, kb: (i, kb, 0)),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), w(lambda i, j, kb: (i, kb, 0)),
                     memory_space=pltpu.VMEM),
    ]
    if has_bias:
        if cfg.bias_batch == 1:
            bmap = lambda i, j, kb: (0, j, kb)
        elif cfg.bias_batch == BIAS_PER_BATCH:
            bmap = lambda i, j, kb: (i // heads, j, kb)
        else:  # BIAS_PER_HEAD
            bmap = lambda i, j, kb: (i, j, kb)
        specs.append(
            pl.BlockSpec((1, block_q, block_k), w(bmap),
                         memory_space=pltpu.VMEM)
        )
    if has_segs:
        # (b, 1, s) layout: the middle singleton keeps the trailing
        # two block dims Mosaic-tileable ((1, block) vs the (8, 128) rule)
        specs.append(pl.BlockSpec(
            (1, 1, block_q), w(lambda i, j, kb: (i // heads, 0, j))
        ))
        specs.append(pl.BlockSpec(
            (1, 1, block_k), w(lambda i, j, kb: (i // heads, 0, kb))
        ))
    if has_dropout:
        specs.append(pl.BlockSpec(
            (1, 1), w(lambda i, j, kb: (0, 0)), memory_space=pltpu.SMEM
        ))
    return specs


def _compiler_params():
    from apex_tpu.ops.common import tpu_compiler_params

    return tpu_compiler_params(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _mask_specialized(run, conds, has_segs, body):
    """Emit ``body(masked=...)`` under ``pl.when`` with mask
    specialization: blocks matching no condition in ``conds`` (causal
    diagonal, padded tail) take the mask-free path — skipping the
    iota/compare/where chain that bounds kernel throughput on the VPU.
    Segment ids force the masked path everywhere; an empty ``conds``
    (non-causal, unpadded) makes every block mask-free."""
    if has_segs or not conds:
        pl.when(run)(lambda: body(masked=bool(has_segs)))
    else:
        need = functools.reduce(jnp.logical_or, conds)
        pl.when(jnp.logical_and(run, need))(lambda: body(masked=True))
        pl.when(jnp.logical_and(run, jnp.logical_not(need)))(
            lambda: body(masked=False))


def _fa_fwd_pallas(q, k, v, bias, qseg, kseg, seed, cfg: _FAConfig):
    bh, psq, d = q.shape
    psk = k.shape[1]
    num_q, num_k = psq // cfg.block_q, psk // cfg.block_k
    # mask specialization assumes padding is confined to the final block
    assert psk - cfg.kv_len < cfg.block_k and psq - cfg.q_len < cfg.block_q
    has_bias = bias is not None
    has_segs = qseg is not None
    has_dropout = cfg.dropout_rate > 0.0
    inputs = [q, k, v]
    if has_bias:
        inputs.append(bias)
    if has_segs:
        inputs.extend([qseg, kseg])
    if has_dropout:
        inputs.append(seed)
    out, lse = pl.pallas_call(
        functools.partial(
            _fa_fwd_kernel, cfg=cfg, num_k=num_k, has_bias=has_bias,
            has_segs=has_segs, has_dropout=has_dropout,
        ),
        grid=(bh, num_q, num_k),
        in_specs=_fwd_in_specs(cfg, d, psq, psk, has_bias, has_segs,
                               has_dropout),
        out_specs=[
            pl.BlockSpec((1, cfg.block_q, d), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, cfg.block_q), lambda i, j, kb: (i, 0, j)),
        ],
        out_shape=[
            shape_struct((bh, psq, d), q.dtype, q, k, v),
            shape_struct((bh, 1, psq), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_q, d), jnp.float32),
            pltpu.VMEM((cfg.block_q, _LANES), jnp.float32),
            pltpu.VMEM((cfg.block_q, _LANES), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*inputs)
    return out, lse[:, 0]


# ---------------------------------------------------------------------------
# Pallas backward
# ---------------------------------------------------------------------------


def _fa_bwd_dkv_kernel(
    *refs, cfg: _FAConfig, num_q: int, has_bias, has_segs, has_dropout,
):
    (q_ref, k_ref, v_ref), rest = refs[:3], refs[3:]
    bias_ref = qseg_ref = kseg_ref = seed_ref = None
    if has_bias:
        bias_ref, rest = rest[0], rest[1:]
    if has_segs:
        (qseg_ref, kseg_ref), rest = rest[:2], rest[2:]
    if has_dropout:
        seed_ref, rest = rest[0], rest[1:]
    do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest

    i, kb, jq = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    block_q, block_k = cfg.block_q, cfg.block_k
    # under causal masking, q blocks strictly above the diagonal band
    # contribute nothing to this k block
    first_jq = (kb * block_k) // block_q if cfg.causal else 0

    @pl.when(jq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _body(masked):
        kblk = k_ref[0].astype(jnp.float32)                # (block_k, d)
        vblk = v_ref[0].astype(jnp.float32)
        qblk = q_ref[0].astype(jnp.float32)                # (block_q, d)
        doblk = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            qblk, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(cfg),
        ) * cfg.sm_scale                                   # (block_q, block_k)
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if masked or has_dropout:
            q_global = jq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            k_global = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
        p = jnp.exp(s - lse)
        if masked:
            mask = jnp.logical_and(
                q_global < cfg.q_len, k_global < cfg.kv_len
            )
            if cfg.causal:
                mask = jnp.logical_and(mask, k_global <= q_global)
            if has_segs:
                mask = jnp.logical_and(
                    mask, qseg_ref[0, 0][:, None] == kseg_ref[0, 0][None, :]
                )
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            doblk, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(cfg),
        )
        if has_dropout:
            keep = _keep_mask(
                seed_ref[0, 0], i, q_global, k_global,
                jnp.uint32(_keep_threshold(cfg.dropout_rate)),
            )
            inv_kp = 1.0 / (1.0 - cfg.dropout_rate)
            p_drop = jnp.where(keep, p, 0.0) * inv_kp
            dp = jnp.where(keep, dp, 0.0) * inv_kp
        else:
            p_drop = p
        dv_acc[...] += jax.lax.dot_general(
            p_drop, doblk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(cfg),
        )
        dz = p * (dp - delta)                              # grad wrt s+bias
        dk_acc[...] += jax.lax.dot_general(
            dz * cfg.sm_scale, qblk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(cfg),
        )

    # Grid roles swapped vs fwd/dq: a q block needs masking iff it
    # intersects the causal diagonal (k_max > q_min) or is the
    # (actually padded) q tail block.  The q-padding mask is
    # load-bearing here — padded q rows carry garbage lse/delta and
    # would otherwise pollute the dk/dv sums — so the tail condition
    # uses jq, not kb.  Padded *k* rows only produce garbage in dk/dv
    # rows that the caller slices off, so kv padding needs no condition
    # in this kernel.
    conds = []
    if cfg.causal:
        conds.append(kb * block_k + (block_k - 1) > jq * block_q)
    if cfg.q_len < num_q * block_q:                         # q padding
        conds.append(jq == num_q - 1)
    _mask_specialized(jq >= first_jq, conds, has_segs, _body)

    @pl.when(jq == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _fa_bwd_dq_kernel(
    *refs, cfg: _FAConfig, num_k: int, has_bias, has_segs, has_dropout,
):
    (q_ref, k_ref, v_ref), rest = refs[:3], refs[3:]
    bias_ref = qseg_ref = kseg_ref = seed_ref = None
    if has_bias:
        bias_ref, rest = rest[0], rest[1:]
    if has_segs:
        (qseg_ref, kseg_ref), rest = rest[:2], rest[2:]
    if has_dropout:
        seed_ref, rest = rest[0], rest[1:]
    if has_bias and cfg.bias_grad:
        do_ref, lse_ref, delta_ref, dq_ref, dbias_ref, dq_acc = rest
    else:
        do_ref, lse_ref, delta_ref, dq_ref, dq_acc = rest
        dbias_ref = None

    i, j, kb = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    block_q, block_k = cfg.block_q, cfg.block_k
    if cfg.causal:
        last_kb = jnp.minimum(num_k - 1, ((j + 1) * block_q - 1) // block_k)
    else:
        last_kb = num_k - 1

    @pl.when(kb == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    # with a bias gradient every block must be written, so the causal
    # block-skip optimization only applies when dbias is not emitted
    # (masking keeps the skipped blocks' contributions at exactly zero
    # either way)
    emit_dbias = dbias_ref is not None
    run = (kb <= last_kb) if not emit_dbias else (kb <= num_k - 1)

    def _body(masked):
        qblk = q_ref[0].astype(jnp.float32)
        kblk = k_ref[0].astype(jnp.float32)
        vblk = v_ref[0].astype(jnp.float32)
        doblk = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            qblk, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(cfg),
        ) * cfg.sm_scale
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if masked or has_dropout:
            q_global = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            k_global = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
        p = jnp.exp(s - lse)
        if masked:
            mask = k_global < cfg.kv_len
            if cfg.causal:
                mask = jnp.logical_and(mask, k_global <= q_global)
            if has_segs:
                mask = jnp.logical_and(
                    mask, qseg_ref[0, 0][:, None] == kseg_ref[0, 0][None, :]
                )
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            doblk, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(cfg),
        )
        if has_dropout:
            keep = _keep_mask(
                seed_ref[0, 0], i, q_global, k_global,
                jnp.uint32(_keep_threshold(cfg.dropout_rate)),
            )
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - cfg.dropout_rate))
        dz = p * (dp - delta)
        if emit_dbias:
            dbias_ref[0] = dz.astype(dbias_ref.dtype)
        dq_acc[...] += jax.lax.dot_general(
            dz * cfg.sm_scale, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(cfg),
        )

    # The emit_dbias path runs above-diagonal blocks too, where the mask
    # is what zeroes dz — those blocks stay on the masked path via the
    # diagonal condition (their k exceeds q).
    conds = []
    if cfg.causal:
        conds.append(kb * block_k + (block_k - 1) > j * block_q)
    if cfg.kv_len < num_k * block_k:                        # kv padding
        conds.append(kb == num_k - 1)
    _mask_specialized(run, conds, has_segs, _body)

    write_kb = (num_k - 1) if emit_dbias else last_kb

    @pl.when(kb == write_kb)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_bwd_pallas(q, k, v, bias, qseg, kseg, seed, out, lse, do,
                   cfg: _FAConfig):
    bh, psq, d = q.shape
    psk = k.shape[1]
    num_q, num_k = psq // cfg.block_q, psk // cfg.block_k
    # mask specialization assumes padding is confined to the final block
    assert psk - cfg.kv_len < cfg.block_k and psq - cfg.q_len < cfg.block_q
    has_bias = bias is not None
    has_segs = qseg is not None
    has_dropout = cfg.dropout_rate > 0.0
    # delta = rowsum(do * o) — cheap, XLA fuses it
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    lse3 = lse[:, None, :]
    delta3 = delta[:, None, :]

    common = [q, k, v]
    if has_bias:
        common.append(bias)
    if has_segs:
        common.extend([qseg, kseg])
    if has_dropout:
        common.append(seed)

    def dkv_specs():
        specs = _fwd_in_specs(cfg, d, psq, psk, has_bias, has_segs,
                              has_dropout, swap_grid=True)
        specs.extend([
            pl.BlockSpec((1, cfg.block_q, d), lambda i, kb, jq: (i, jq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, cfg.block_q), lambda i, kb, jq: (i, 0, jq)),
            pl.BlockSpec((1, 1, cfg.block_q), lambda i, kb, jq: (i, 0, jq)),
        ])
        return specs

    dk, dv = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkv_kernel, cfg=cfg, num_q=num_q, has_bias=has_bias,
            has_segs=has_segs, has_dropout=has_dropout,
        ),
        grid=(bh, num_k, num_q),
        in_specs=dkv_specs(),
        out_specs=[
            pl.BlockSpec((1, cfg.block_k, d), lambda i, kb, jq: (i, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cfg.block_k, d), lambda i, kb, jq: (i, kb, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            shape_struct((bh, psk, d), k.dtype, q, k, v, do),
            shape_struct((bh, psk, d), v.dtype, q, k, v, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_k, d), jnp.float32),
            pltpu.VMEM((cfg.block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*common, do, lse3, delta3)

    emit_dbias = has_bias and cfg.bias_grad
    dq_out_specs = [
        pl.BlockSpec((1, cfg.block_q, d), lambda i, j, kb: (i, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    dq_out_shape = [shape_struct((bh, psq, d), q.dtype, q, k, v, do)]
    if emit_dbias:
        dq_out_specs.append(
            pl.BlockSpec((1, cfg.block_q, cfg.block_k),
                         lambda i, j, kb: (i, j, kb),
                         memory_space=pltpu.VMEM)
        )
        dq_out_shape.append(
            shape_struct((bh, psq, psk), jnp.float32, q, k, v, do)
        )

    dq_specs = _fwd_in_specs(cfg, d, psq, psk, has_bias, has_segs,
                             has_dropout)
    dq_specs.extend([
        pl.BlockSpec((1, cfg.block_q, d), lambda i, j, kb: (i, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, cfg.block_q), lambda i, j, kb: (i, 0, j)),
        pl.BlockSpec((1, 1, cfg.block_q), lambda i, j, kb: (i, 0, j)),
    ])
    res = pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, cfg=cfg, num_k=num_k, has_bias=has_bias,
            has_segs=has_segs, has_dropout=has_dropout,
        ),
        grid=(bh, num_q, num_k),
        in_specs=dq_specs,
        out_specs=dq_out_specs if emit_dbias else dq_out_specs[0],
        out_shape=dq_out_shape if emit_dbias else dq_out_shape[0],
        compiler_params=_compiler_params(),
        scratch_shapes=[pltpu.VMEM((cfg.block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*common, do, lse3, delta3)
    if emit_dbias:
        dq, dbias = res
    else:
        dq, dbias = res, None
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# custom_vjp wrapper (flattened (b*h, s, d) layout)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def _flash(q, k, v, bias, qseg, kseg, seed, cfg):
    out, _ = _fa_fwd_pallas(q, k, v, bias, qseg, kseg, seed, cfg)
    return out


def _flash_fwd(q, k, v, bias, qseg, kseg, seed, cfg):
    out, lse = _fa_fwd_pallas(q, k, v, bias, qseg, kseg, seed, cfg)
    return out, (q, k, v, bias, qseg, kseg, seed, out, lse)


def _int_zero(x):
    return (
        None if x is None
        else np.zeros(x.shape, jax.dtypes.float0)
    )


def _flash_bwd(cfg, res, do):
    q, k, v, bias, qseg, kseg, seed, out, lse = res
    dq, dk, dv, dbias = _fa_bwd_pallas(
        q, k, v, bias, qseg, kseg, seed, out, lse, do, cfg
    )
    if bias is not None and not cfg.bias_grad:
        # constant-mask contract: caller declared the bias non-trainable
        dbias = jnp.zeros_like(bias)
    elif bias is not None:
        # the kernel emits per-(b*h) score grads; fold back to the
        # flattened-bias batching the primal used
        bh, psq, psk = dbias.shape
        if cfg.bias_batch == 1:
            dbias = jnp.sum(dbias, axis=0, keepdims=True)
        elif cfg.bias_batch == BIAS_PER_BATCH:
            dbias = dbias.reshape(
                bh // cfg.heads, cfg.heads, psq, psk
            ).sum(axis=1)
        dbias = dbias.astype(bias.dtype)
    return (dq, dk, dv, dbias, _int_zero(qseg), _int_zero(kseg),
            _int_zero(seed))


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Public entry point
# ---------------------------------------------------------------------------


def _pad_seq(x, pad, axis=1):
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
    q_segment_ids: Optional[jnp.ndarray] = None,
    kv_segment_ids: Optional[jnp.ndarray] = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
    bias_requires_grad: bool = True,
    block_q: int = 512,
    block_k: int = 1024,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """Flash attention over ``(batch, heads, seq, head_dim)``.

    ``implementation`` is ``"pallas"`` (the streamed flash kernel),
    ``"xla"`` (reference path, also the CPU fallback), ``"short"`` (the
    single-pass short-sequence kernel family in
    ``ops/attention_short.py`` — the analog of the reference's
    per-seqlen {128,256,384,512} fmha kernels), or ``"mid"`` (the
    pipelined mid-sequence kernel in ``ops/attention_mid.py``: smaller
    streamed k-blocks + batch*head packing + causal block-skipping for
    the 512 < s <= ~2048 band), or ``"decode"`` (the fourth rung,
    ``ops/attention_decode.py``: tiny-q generation attention against a
    long cache — explicit-only, forward-only, no bias/segments/dropout;
    serving callers with a paged cache call ``fmha_decode`` directly);
    default picks by platform and the
    measured three-tier dispatch ladder short → mid → flash
    (crossovers ``FMHA_SHORT_MAX_SEQ`` / ``FMHA_MID_MAX_SEQ``,
    env-overridable — see ``docs/attention.md``).
    ``block_q``/``block_k`` only apply to the flash kernel (the short
    kernel holds the whole sequence and blocks the batch*heads
    dimension instead; the mid kernel sizes its own blocks).

    ``bias`` is an additive score bias broadcastable from
    ``(1|b, 1|h, sq, sk)``; it is differentiable by default (the backward
    pass then materialises per-head score-grad blocks, so prefer
    ``segment_ids`` over huge bias masks for long-sequence varlen).
    Pass ``bias_requires_grad=False`` for constant masks: the bias
    cotangent is then hard zero and the backward keeps the pure
    flash-attention memory profile.
    ``q_segment_ids``/``kv_segment_ids`` are ``(b, sq)``/``(b, sk)``
    int32 tokens-attend-within-equal-id masks — the TPU-native varlen
    API (reference: cu_seqlens, apex/contrib/fmha/fmha.py:33-80).
    ``dropout_rate``/``dropout_seed`` apply probability dropout inside
    the kernel with a counter-based hash (reference: philox.h) that the
    backward pass replays exactly; the same seed on the XLA path draws
    the identical mask.

    Default block sizes come from the on-chip sweep in KERNELS_TPU.json
    (v5e: 1024x1024 is fastest, 512x1024 is within ~5% with more VMEM
    headroom for the bias/dropout variants); both are clamped to the
    sequence lengths.
    """
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("segment ids must be given for both q and kv")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if bias is not None and bias.ndim < 4:
        bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
    from apex_tpu.ops.common import KernelLoweringError, run_kernel

    if pl is None and implementation in ("pallas", "short", "mid"):
        raise KernelLoweringError(
            f"implementation={implementation!r} requested but Pallas "
            "failed to import"
        )

    def _short_path(forced: bool):
        from apex_tpu.ops.attention_short import fmha_short

        return fmha_short(
            q, k, v, causal=causal, sm_scale=sm_scale, bias=bias,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
            bias_requires_grad=bias_requires_grad,
            implementation="pallas" if forced else None,
        )

    def _mid_path(forced: bool):
        from apex_tpu.ops.attention_mid import fmha_mid

        return fmha_mid(
            q, k, v, causal=causal, sm_scale=sm_scale, bias=bias,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
            bias_requires_grad=bias_requires_grad,
            implementation="pallas" if forced else None,
        )

    if implementation == "decode":
        # the fourth rung (ops/attention_decode.py): tiny-q against a
        # long cache, here over contiguous K/V viewed as trivially-paged
        # storage.  Decode callers hold no trainable bias/segments and
        # never differentiate through the cache, so the rung is
        # explicit-only — the training ladder's measured crossovers
        # stay untouched.  Serving callers with a real page table call
        # fmha_decode directly.
        if (bias is not None or q_segment_ids is not None
                or dropout_rate > 0.0):
            raise ValueError(
                "implementation='decode' supports plain (optionally "
                "causal) attention only — no bias/segments/dropout"
            )
        from apex_tpu.ops.attention_decode import decode_contiguous

        return decode_contiguous(q, k, v, causal=causal, sm_scale=sm_scale)
    if implementation == "short":
        return _short_path(forced=True)
    if implementation == "mid":
        return _mid_path(forced=True)
    impl = implementation or default_implementation()
    if (
        implementation is None
        and impl == "pallas"
        and q.dtype == jnp.float32
        and q.shape[2] <= FLASH_FP32_XLA_MAX_SEQ
    ):
        # measured dispatch window (KERNELS_TPU.json, fp32 entries):
        # fp32 inputs run the kernel dots at Precision.HIGHEST for
        # parity, which loses to XLA at s=1024 (0.8x fwd) and wins by
        # s=4096 (>2x fwd, growing with s); the boundary is the largest
        # measured losing shape.  Auto mode routes accordingly — the
        # analog of the reference's kernel-availability windows
        # (apex/transformer/functional/fused_softmax.py:151-171)
        impl = "xla"
    if implementation is None and impl == "pallas":
        from apex_tpu.ops.attention_short import short_seq_threshold

        thr = short_seq_threshold()
        if q.shape[2] <= thr and k.shape[2] <= thr:
            # short-sequence window: the whole kv fits one k-block, so
            # the single-pass fmha-short kernel drops the online-softmax
            # machinery and packs (batch*heads) programs per grid step
            # (crossover constant FMHA_SHORT_MAX_SEQ, recorded/gated by
            # tools/kernel_validation.py).  Note ordering: the fp32→XLA
            # window above fires first, so fp32 short sequences keep
            # their measured XLA routing until a capture says otherwise.
            return _short_path(forced=False)
        from apex_tpu.ops.attention_mid import mid_seq_threshold

        mthr = mid_seq_threshold()
        if max(q.shape[2], k.shape[2]) <= mthr:
            # mid-sequence window (short crossover < s <= mid
            # crossover): the flash kernel's measured-optimal
            # 1024x1024 blocks degenerate to <= 2 k-blocks here — no
            # software pipelining, no causal block-skip (PROFILE_r05:
            # 10.2 TF/s at s=1024 causal vs ~50 at s>=4096) — so the
            # pipelined mid kernel streams smaller k-blocks with
            # batch*head packing instead (crossover constant
            # FMHA_MID_MAX_SEQ, recorded/gated by kernel_validation;
            # APEX_TPU_FMHA_MID_MAX_SEQ=0 pins this window back to
            # the flash kernel bit-identically)
            return _mid_path(forced=False)
    if pl is None:
        impl = "xla"

    def _xla_path():
        return mha_reference(
            q, k, v, causal=causal, sm_scale=sm_scale, bias=bias,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )

    def _pallas_path():
        return _flash_attention_pallas(
            q, k, v, causal, sm_scale, bias, q_segment_ids,
            kv_segment_ids, dropout_rate, dropout_seed,
            bias_requires_grad, block_q, block_k,
        )

    return run_kernel(
        "flash_attention", _pallas_path, _xla_path, implementation, impl
    )


# fp32 block-area cap shared with the kernel-validation sweep (which
# must skip configs the wrapper would clamp, or it double-times the
# clamped program under multiple labels)
FLASH_FP32_MAX_BLOCK_AREA = 512 * 1024


def _clamp_blocks(dtype, block_q: int, block_k: int):
    """Clamp the (block_q, block_k) area for fp32 inputs.

    The backward kernels keep several (block_q, block_k) fp32 score-space
    temporaries live at once (s, p, dp, dz); at 1024x1024 fp32 blocks
    that stack reaches ~18.3 MB and exceeds Mosaic's 16 MB scoped-vmem
    limit (measured compile failure, r5 kernel sweep).  512x1024 — the
    shipped default and the area every committed fp32 sweep row was
    measured at — halves each temporary to 2 MB and compiles at every
    benchmarked shape, so fp32 requests above that area are clamped
    rather than left to fail in the compiler.  bf16 keeps the caller's
    blocks: its temporaries stay fp32 in-kernel but the sweep shows
    1024x1024 compiling and winning there (KERNELS_TPU.json).
    """
    if dtype == jnp.float32:
        while block_q * block_k > FLASH_FP32_MAX_BLOCK_AREA:
            if block_q >= block_k:
                block_q //= 2
            else:
                block_k //= 2
    return block_q, block_k


def _flash_attention_pallas(
    q, k, v, causal, sm_scale, bias, q_segment_ids, kv_segment_ids,
    dropout_rate, dropout_seed, bias_requires_grad, block_q, block_k,
):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = (1.0 / d**0.5) if sm_scale is None else float(sm_scale)
    block_q, block_k = _clamp_blocks(q.dtype, block_q, block_k)
    block_q = min(block_q, max(sq, 1))
    block_k = min(block_k, max(sk, 1))
    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    # pad head_dim to the 128-lane tile; zero columns do not change
    # q@k^T, and padded output columns are sliced off
    pad_d = (-d) % _LANES
    if pad_d:
        padd = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        q, k, v = padd(q), padd(k), padd(v)

    flat = lambda x: x.reshape(b * h, x.shape[2], x.shape[3])
    qf = _pad_seq(flat(q), pad_q)
    kf = _pad_seq(flat(k), pad_k)
    vf = _pad_seq(flat(v), pad_k)

    bias_flat = None
    bias_batch = 0
    if bias is not None:
        bb, bs_h, bsq, bsk = bias.shape
        bias4 = jnp.broadcast_to(bias, (bb, bs_h, sq, sk))
        if bb == 1 and bs_h == 1:
            bias_flat, bias_batch = bias4.reshape(1, sq, sk), 1
        elif bs_h == 1:
            bias_flat, bias_batch = bias4.reshape(b, sq, sk), BIAS_PER_BATCH
        else:
            bias4 = jnp.broadcast_to(bias, (b, h, sq, sk))
            bias_flat = bias4.reshape(b * h, sq, sk)
            bias_batch = BIAS_PER_HEAD
        bias_flat = _pad_seq(_pad_seq(bias_flat, pad_q, axis=1), pad_k, axis=2)

    qseg = kseg = None
    if q_segment_ids is not None:
        qseg = _pad_seq(q_segment_ids.astype(jnp.int32), pad_q, axis=1)
        # padded kv positions are masked by kv_len already; pad ids with -1
        # so they also never match a real segment
        kseg = jnp.pad(
            kv_segment_ids.astype(jnp.int32), ((0, 0), (0, pad_k)),
            constant_values=-1,
        ) if pad_k else kv_segment_ids.astype(jnp.int32)
        # (b, 1, s): the singleton keeps the trailing block dims tileable
        qseg, kseg = qseg[:, None, :], kseg[:, None, :]

    seed_arr = None
    if dropout_rate > 0.0:
        seed_arr = jnp.asarray(dropout_seed, jnp.uint32).reshape(1, 1)

    cfg = _FAConfig(
        sm_scale=scale, causal=causal, dropout_rate=float(dropout_rate),
        block_q=block_q, block_k=block_k, q_len=sq, kv_len=sk, heads=h,
        bias_batch=bias_batch, bias_grad=bool(bias_requires_grad),
        hi_precision=(q.dtype == jnp.float32),
    )
    out = _flash(qf, kf, vf, bias_flat, qseg, kseg, seed_arr, cfg)
    if pad_q:
        out = out[:, :sq]
    out = out.reshape(b, h, sq, d + pad_d)
    if pad_d:
        out = out[..., :d]
    return out

"""Flash attention for TPU (Pallas), plus a reference XLA path.

Capability match — and supersession — of the reference's attention stack:
``fmhalib`` (apex/contrib/csrc/fmha/, fp16, seqlen<=512, SM80-only) and the
eight ``fast_*_multihead_attn`` extensions
(apex/contrib/csrc/multihead_attn/).  Those kernels materialise the
(sq, sk) score matrix per head; flash attention never does, so the TPU
design has no seqlen window: one online-softmax kernel covers every
sequence length, causal or not, bf16-first.

Layout: ``(batch, heads, seq, head_dim)``.  Softmax statistics are fp32;
the accumulator is fp32; output matches the input dtype.

Kernel strategy (chosen for VMEM residency, see pallas_guide):
- forward: grid ``(batch*heads, q_blocks)``; K/V for the whole sequence
  sit in VMEM per program (S=8k in bf16 is ~2 MB each at d=128) and the
  kernel walks K in ``block_k`` slices with a ``fori_loop`` whose trip
  count shrinks under causal masking.
- backward: two kernels — dK/dV over ``(batch*heads, k_blocks)`` and dQ
  over ``(batch*heads, q_blocks)`` — both replaying scores from the saved
  log-sum-exp, the standard flash-attention-2 recomputation split.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.common import shape_struct
from apex_tpu.utils.platform import default_implementation, is_tpu

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None

__all__ = ["flash_attention", "mha_reference"]

_NEG_INF = -1e30


def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Plain XLA attention with fp32 softmax — the correctness reference,
    playing the role of the reference's pure-PyTorch ``impl='default'``
    path (apex/contrib/multihead_attn/self_multihead_attn_func.py)."""
    d = q.shape[-1]
    scale = (1.0 / d**0.5) if sm_scale is None else sm_scale
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2:]
        q_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(k_idx > q_idx, _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)




def _interpret() -> bool:
    """Interpreter-mode Pallas off-TPU: the kernel bodies still run (and
    are testable) on CPU, at interpreter speed."""
    return not is_tpu()


# ---------------------------------------------------------------------------
# Pallas forward
# ---------------------------------------------------------------------------


def _fa_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref,
    *, sm_scale, causal, block_q, block_k, kv_len,
):
    j = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (block_q, d)
    d = q.shape[-1]
    num_k = pl.cdiv(kv_len, block_k)
    if causal:
        # blocks wholly above the diagonal contribute nothing
        num_k = jnp.minimum(
            num_k, pl.cdiv((j + 1) * block_q, block_k)
        )

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                 # (block_q, block_k)
        k_global = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        mask = k_global < kv_len
        if causal:
            q_global = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            mask = jnp.logical_and(mask, k_global <= q_global)
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_k, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l))[:, 0]


def _fa_fwd_pallas(q, k, v, sm_scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    kv_len = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, kv_len)
    pad_q = (-sq) % block_q
    pad_k = (-kv_len) % block_k
    qp = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0))) if pad_k else v
    psq, psk = sq + pad_q, kv_len + pad_k
    grid = (bh, psq // block_q)
    out, lse = pl.pallas_call(
        functools.partial(
            _fa_fwd_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, kv_len=kv_len,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, psk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, psk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            shape_struct((bh, psq, d), q.dtype, qp, kp, vp),
            shape_struct((bh, 1, psq), jnp.float32, qp, kp, vp),
        ],
        interpret=_interpret(),
    )(qp, kp, vp)
    lse = lse[:, 0]
    if pad_q:
        out, lse = out[:, :sq], lse[:, :sq]
    return out, lse


# ---------------------------------------------------------------------------
# Pallas backward
# ---------------------------------------------------------------------------


def _fa_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, sm_scale, causal, block_q, block_k, q_len,
):
    kb = pl.program_id(1)
    kblk = k_ref[0].astype(jnp.float32)                   # (block_k, d)
    vblk = v_ref[0].astype(jnp.float32)
    d = kblk.shape[-1]
    num_q = pl.cdiv(q_len, block_q)
    start_q = 0
    if causal:
        start_q = (kb * block_k) // block_q

    def body(jq, carry):
        dk, dv = carry
        qblk = q_ref[0, pl.ds(jq * block_q, block_q), :].astype(jnp.float32)
        doblk = do_ref[0, pl.ds(jq * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(jq * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(jq * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            qblk, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                      # (block_q, block_k)
        q_global = jq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0
        )
        k_global = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        mask = q_global < q_len
        if causal:
            mask = jnp.logical_and(mask, k_global <= q_global)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv = dv + jax.lax.dot_general(
            p, doblk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            doblk, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds, qblk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    dk0 = jnp.zeros((kblk.shape[0], d), jnp.float32)
    dv0 = jnp.zeros((vblk.shape[0], d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, num_q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fa_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, sm_scale, causal, block_q, block_k, kv_len,
):
    j = pl.program_id(1)
    qblk = q_ref[0].astype(jnp.float32)                   # (block_q, d)
    doblk = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]
    d = qblk.shape[-1]
    num_k = pl.cdiv(kv_len, block_k)
    if causal:
        num_k = jnp.minimum(num_k, pl.cdiv((j + 1) * block_q, block_k))

    def body(kb, dq):
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            qblk, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        k_global = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        mask = k_global < kv_len
        if causal:
            q_global = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            mask = jnp.logical_and(mask, k_global <= q_global)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            doblk, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        return dq + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(
        0, num_k, body, jnp.zeros((qblk.shape[0], d), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _fa_bwd_pallas(q, k, v, out, lse, do, sm_scale, causal,
                   block_q, block_k):
    bh, sq, d = q.shape
    kv_len = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, kv_len)
    pad_q = (-sq) % block_q
    pad_k = (-kv_len) % block_k
    # delta = rowsum(do * o) — cheap, XLA fuses it
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    padq = lambda x: jnp.pad(x, ((0, 0), (0, pad_q), (0, 0))) if pad_q else x
    padk = lambda x: jnp.pad(x, ((0, 0), (0, pad_k), (0, 0))) if pad_k else x
    qp, dop = padq(q), padq(do)
    kp, vp = padk(k), padk(v)
    lsep = jnp.pad(lse, ((0, 0), (0, pad_q))) if pad_q else lse
    deltap = jnp.pad(delta, ((0, 0), (0, pad_q))) if pad_q else delta
    lsep = lsep[:, None, :]
    deltap = deltap[:, None, :]
    psq, psk = sq + pad_q, kv_len + pad_k

    dk, dv = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, q_len=sq,
        ),
        grid=(bh, psk // block_k),
        in_specs=[
            pl.BlockSpec((1, psq, d), lambda i, kb: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, psq, d), lambda i, kb: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, psq), lambda i, kb: (i, 0, 0)),
            pl.BlockSpec((1, 1, psq), lambda i, kb: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            shape_struct((bh, psk, d), k.dtype, qp, kp, vp, dop),
            shape_struct((bh, psk, d), v.dtype, qp, kp, vp, dop),
        ],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)

    dq = pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, kv_len=kv_len,
        ),
        grid=(bh, psq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, psk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, psk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=shape_struct((bh, psq, d), q.dtype, qp, kp, vp, dop),
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)

    if pad_q:
        dq = dq[:, :sq]
    if pad_k:
        dk, dv = dk[:, :kv_len], dv[:, :kv_len]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper (flattened (b*h, s, d) layout)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, sm_scale, causal, block_q, block_k):
    out, _ = _fa_fwd_pallas(q, k, v, sm_scale, causal, block_q, block_k)
    return out


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    out, lse = _fa_fwd_pallas(q, k, v, sm_scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _fa_bwd_pallas(
        q, k, v, out, lse, do, sm_scale, causal, block_q, block_k
    )
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
    block_q: int = 256,
    block_k: int = 256,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """Flash attention over ``(batch, heads, seq, head_dim)``.

    ``implementation`` is ``"pallas"`` (TPU kernel) or ``"xla"``
    (reference path, also the CPU fallback); default picks by platform.
    ``bias`` (additive mask) currently routes to the XLA path.
    """
    impl = implementation or default_implementation()
    if impl != "pallas" or pl is None or bias is not None:
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                             bias=bias)
    b, h, sq, d = q.shape
    scale = (1.0 / d**0.5) if sm_scale is None else float(sm_scale)
    # pad head_dim to the 128-lane tile; zero columns do not change
    # q@k^T, and padded output columns are sliced off
    pad_d = (-d) % 128
    if pad_d:
        padd = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        q, k, v = padd(q), padd(k), padd(v)
    flat = lambda x: x.reshape(b * h, x.shape[2], x.shape[3])
    out = _flash(flat(q), flat(k), flat(v), scale, causal,
                 block_q, block_k)
    out = out.reshape(b, h, sq, d + pad_d)
    if pad_d:
        out = out[..., :d]
    return out

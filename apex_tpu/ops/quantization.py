"""Block-wise int8 quantization for compressed gradient collectives.

The hierarchical RS(ici) → AR(dcn) → AG(ici) decomposition
(:func:`apex_tpu.parallel.distributed._hierarchical_psum`) already cuts
DCN traffic to 1/ici of the gradient — but every byte that does cross
the slow axis is still full-width.  EQuARX (PAPERS.md) shows a
block-quantized all-reduce recovers most of that bandwidth on XLA/TPU
with negligible quality loss, and the adaptive-summation line of work
(Maleki et al.) is why any lossy reduction here carries an explicit
error-feedback residual: the quantization error of step *t* is added
back into the gradient of step *t+1*, so the bias is compensated
instead of accumulated.

This module is the numeric core plus the one compressed collective:

- :func:`quantize_blockwise` / :func:`dequantize_blockwise` — flat
  int8 values with one fp32 scale per ``block_size`` elements,
  deterministic (round-half-even) or stochastic rounding, bf16/fp32
  in/out;
- :class:`CompressionConfig` — the ``compression=`` knob's value
  (the string ``"int8"`` is accepted everywhere as the default config);
- :func:`quantized_psum` — an int8 all-reduce over ONE mesh axis,
  built for the DCN leg: quantize once, exchange int8 + scales with
  ``all_to_all`` (the reduce-scatter phase), accumulate the exact
  int8 x fp32-scale products, re-quantize the reduced shard once, and
  ``all_gather`` int8 + scales back.  Only the tiny fp32 scale
  sidecar (``4 / block_size`` bytes per element) crosses the axis at
  full width, so bytes-on-wire drop ~4x vs an fp32 psum;
- :func:`quantized_reduce_scatter` / :func:`quantized_all_gather` —
  the EQuARX ICI half: the same int8-values + fp32-scales wire format
  applied to ONE leg each, chunk-preserving (rank *r* receives exactly
  the elements ``lax.psum_scatter(tiled)`` would give it, for any
  chunk size — blocks never straddle row boundaries, so enabling
  compression never moves a shard boundary).  ``CompressionConfig(
  ici_legs=True)`` makes the hierarchical reduce run BOTH its ICI
  legs through these (see ``_hierarchical_psum``), with their own
  error-feedback residuals (``ici_push`` / ``ici_pull``) beside the
  DCN pair.

Deviation from the ISSUE's "(int32-accumulated values, scales)"
sketch: each sender keeps its OWN per-block scales (no extra
max-scale collective on the slow axis, and a small-magnitude sender
is not coarsened by a large-magnitude peer's amax); the receiver then
accumulates ``int8 * fp32_scale`` products, which is at least as
accurate as sharing scales and summing in int32, for any axis size
that fits training practice.

Everything here is pure ``jnp``/``lax`` — the collective must be
called inside ``shard_map`` (or ``pmap``) with the axis bound.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp

__all__ = [
    "CompressionConfig",
    "as_compression_config",
    "quantize_blockwise",
    "dequantize_blockwise",
    "quantize_rows",
    "dequantize_rows",
    "pack_int4",
    "unpack_int4",
    "quantize_rows_int4",
    "dequantize_rows_int4",
    "comm_residual_sizes",
    "hierarchical_residual_sizes",
    "zero3_residual_sizes",
    "init_residual",
    "quantized_psum",
    "quantized_reduce_scatter",
    "quantized_all_gather",
]

_INT8_MAX = 127.0
_INT4_MAX = 7.0


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """Configuration for compressed (quantized) collectives.

    ``method``: only ``"int8"`` today.  ``block_size``: elements per
    fp32 scale (wire overhead = 4/block_size bytes per element).
    ``rounding``: ``"nearest"`` (deterministic, round-half-even) or
    ``"stochastic"`` (unbiased; pass a fresh ``key`` per step, or
    thread comm state so the built-in step counter derives one).
    ``error_feedback``: carry the per-device quantization residual as
    explicit state and add it back next step (strongly recommended for
    training; requires the caller to thread a state pytree).
    ``ici_legs``: ALSO compress the reduce-scatter/all-gather legs of
    the hierarchical reduce (EQuARX's ICI half) — default off, which
    leaves those legs full-width exactly as before; with error
    feedback the residual state then carries two extra buffers
    (``ici_push``/``ici_pull``) and must be rebuilt with the same
    config (:func:`~apex_tpu.parallel.distributed.init_comm_state`
    sizes them from the config automatically).
    """

    method: str = "int8"
    block_size: int = 256
    rounding: str = "nearest"
    error_feedback: bool = True
    ici_legs: bool = False

    def __post_init__(self):
        if self.method != "int8":
            raise ValueError(
                f"unsupported compression method {self.method!r} "
                "(only 'int8')"
            )
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        if self.rounding not in ("nearest", "stochastic"):
            raise ValueError(
                f"rounding must be 'nearest' or 'stochastic', got "
                f"{self.rounding!r}"
            )


def as_compression_config(
    compression: Union[None, str, CompressionConfig]
) -> Optional[CompressionConfig]:
    """Normalize the ``compression=`` knob: None | "int8" | config."""
    if compression is None:
        return None
    if isinstance(compression, CompressionConfig):
        return compression
    if isinstance(compression, str):
        return CompressionConfig(method=compression)
    raise ValueError(
        f"compression must be None, 'int8' or a CompressionConfig, got "
        f"{compression!r}"
    )


def _axis_size(axis_name) -> int:
    from apex_tpu._compat import axis_size

    return int(axis_size(axis_name))


def _blocks(flat: jnp.ndarray, block_size: int) -> jnp.ndarray:
    n = flat.size
    pad = (-n) % block_size
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad,), flat.dtype)]
        )
    return flat.reshape(-1, block_size)


def quantize_blockwise(
    x: jnp.ndarray,
    block_size: int = 256,
    rounding: str = "nearest",
    key: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize to int8 with one fp32 scale per block.

    ``x`` (any shape, bf16/fp32) is flattened; blocks of
    ``block_size`` elements share ``scale = max|block| / 127``
    (all-zero blocks get scale 1 so dequantization is exact).
    Returns ``(values, scales)``: ``values`` int8 with ``x``'s shape,
    ``scales`` fp32 of shape ``(ceil(x.size / block_size),)``.

    ``rounding="nearest"`` is deterministic (ties to even);
    ``"stochastic"`` computes ``floor(v + u)``, ``u ~ U[0, 1)`` from
    ``key`` (required), which is unbiased: ``E[q] = v``.
    """
    shape = x.shape
    xf = _blocks(x.reshape(-1).astype(jnp.float32), block_size)
    amax = jnp.max(jnp.abs(xf), axis=1)
    scales = jnp.where(amax > 0.0, amax / _INT8_MAX, 1.0)
    v = jnp.clip(xf / scales[:, None], -_INT8_MAX, _INT8_MAX)
    if rounding == "stochastic":
        if key is None:
            raise ValueError("stochastic rounding requires a PRNG key")
        u = jax.random.uniform(key, v.shape, jnp.float32)
        q = jnp.floor(v + u)
    else:
        q = jnp.round(v)
    q = jnp.clip(q, -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q.reshape(-1)[: int(jnp.size(x))].reshape(shape), scales


def dequantize_blockwise(
    values: jnp.ndarray,
    scales: jnp.ndarray,
    block_size: int = 256,
    dtype: Any = jnp.float32,
) -> jnp.ndarray:
    """Inverse of :func:`quantize_blockwise` (up to rounding error)."""
    shape = values.shape
    q = _blocks(values.reshape(-1).astype(jnp.float32), block_size)
    out = q * scales[:, None]
    return out.reshape(-1)[: int(jnp.size(values))].reshape(shape).astype(
        dtype
    )


def _check_row_blocks(n: int, block_size: int, leaf: Optional[str],
                      shape) -> None:
    """The weight-pool seam's block validation: a block size that does
    not divide the row length would silently pad (fine for the
    collectives, which own both ends of the wire) but corrupts a
    weight whose kernel tiles assume whole blocks.  Callers that name
    their ``leaf`` opt into the strict contract and get an actionable
    error instead of a reshape traceback deep inside a jit."""
    if leaf is None:
        return
    if block_size < 1 or n % block_size:
        raise ValueError(
            f"block_size={block_size} does not divide the row length "
            f"of leaf {leaf!r} (shape {tuple(shape)}, rows of "
            f"{n} elements): the in-kernel dequant tiles need whole "
            f"blocks — pick a block_size that divides {n} (e.g. a "
            f"power of two that divides the hidden/ffn width)")


def quantize_rows(
    x: jnp.ndarray,
    block_size: int = 256,
    rounding: str = "nearest",
    key: Optional[jnp.ndarray] = None,
    *,
    leaf: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-ROW block-wise quantize of a 2-D ``(rows, n)`` array: blocks
    never straddle row boundaries, so each row can be exchanged (and
    dequantized) independently of its neighbours — the property the
    chunk-preserving RS/AG legs need.  Same per-block math as
    :func:`quantize_blockwise`; a single row is bit-identical to it.
    Returns ``(values int8 (rows, n), scales fp32 (rows,
    ceil(n/block_size)))``.

    ``leaf`` (the weight-pool seam): when given, ``block_size`` MUST
    divide ``n`` exactly — a violation raises a :class:`ValueError`
    naming the leaf and its shape (the silent zero-padding the
    collectives rely on would desynchronize an in-kernel dequant's
    block tiling)."""
    rows, n = x.shape
    _check_row_blocks(n, block_size, leaf, x.shape)
    nb = max(-(-n // block_size), 1)
    pad = nb * block_size - n
    xf = x.astype(jnp.float32)
    if pad:
        xf = jnp.concatenate(
            [xf, jnp.zeros((rows, pad), jnp.float32)], axis=1
        )
    xb = xf.reshape(rows, nb, block_size)
    amax = jnp.max(jnp.abs(xb), axis=2)
    scales = jnp.where(amax > 0.0, amax / _INT8_MAX, 1.0)
    v = jnp.clip(xb / scales[:, :, None], -_INT8_MAX, _INT8_MAX)
    if rounding == "stochastic":
        if key is None:
            raise ValueError("stochastic rounding requires a PRNG key")
        u = jax.random.uniform(key, v.shape, jnp.float32)
        q = jnp.floor(v + u)
    else:
        q = jnp.round(v)
    q = jnp.clip(q, -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q.reshape(rows, nb * block_size)[:, :n], scales


def dequantize_rows(
    values: jnp.ndarray,
    scales: jnp.ndarray,
    block_size: int = 256,
    dtype: Any = jnp.float32,
) -> jnp.ndarray:
    """Inverse of :func:`quantize_rows` (up to rounding error)."""
    rows, n = values.shape
    expand = jnp.repeat(scales, block_size, axis=1)[:, :n]
    return (values.astype(jnp.float32) * expand).astype(dtype)


# --------------------------------------------------------------- int4
def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """Pack int4 values (int8 storage, each in ``[-8, 7]``) two nibbles
    per byte: packed column ``c`` holds column ``c`` in its LOW nibble
    and column ``c + n/2`` in its HIGH nibble (the halves layout).
    Pairing across the row's halves — rather than adjacent columns —
    means :func:`unpack_int4` reassembles the original column order
    with ONE concatenation, no interleave: exactly the shape of op a
    Pallas kernel can run on the lane dimension in VMEM.  Returns int8
    ``(rows, n // 2)``; ``n`` must be even."""
    rows, n = q.shape
    if n % 2:
        raise ValueError(
            f"pack_int4 needs an even row length to pair nibbles, got "
            f"shape {tuple(q.shape)}")
    x = q.astype(jnp.int32)
    lo = x[:, : n // 2] & 0xF
    hi = x[:, n // 2:] & 0xF
    p = lo | (hi << 4)
    # two's-complement re-interpretation into int8 storage (values
    # 128..255 map to -128..-1) — kept deterministic instead of
    # relying on astype overflow behavior
    return jnp.where(p < 128, p, p - 256).astype(jnp.int8)


def unpack_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_int4`: int8 ``(rows, n/2)`` packed bytes
    → int8 ``(rows, n)`` values in ``[-8, 7]``, exact for every
    nibble.  Sign extension is the shift-free ``(x ^ 8) - 8`` form —
    pure elementwise int ops, VMEM-friendly."""
    x = packed.astype(jnp.int32) & 0xFF
    lo = ((x & 0xF) ^ 8) - 8
    hi = (((x >> 4) & 0xF) ^ 8) - 8
    return jnp.concatenate([lo, hi], axis=-1).astype(jnp.int8)


def quantize_rows_int4(
    x: jnp.ndarray,
    block_size: int = 128,
    *,
    leaf: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row block-wise int4 quantize of a 2-D ``(rows, n)`` array:
    the :func:`quantize_rows` discipline at 4-bit width (``scale =
    max|block| / 7``, round-half-even, all-zero blocks get scale 1),
    packed two nibbles per byte by :func:`pack_int4`.  Returns
    ``(packed int8 (rows, n // 2), scales fp32 (rows, n /
    block_size))``.

    Constraints (checked loudly): ``block_size`` must be EVEN — an odd
    block leaves one nibble of every block unpaired, which the
    two-per-byte packing cannot represent; ``n`` must be a multiple of
    ``2 * block_size`` so the packed halves layout keeps whole scale
    blocks inside each half (the in-kernel dequant's tiling contract).
    ``leaf`` names the owning weight in the error message."""
    rows, n = x.shape
    at = "" if leaf is None else f" of leaf {leaf!r}"
    if block_size < 2 or block_size % 2:
        raise ValueError(
            f"int4 block_size must be even (two nibbles per byte — an "
            f"odd block cannot pair its last nibble), got "
            f"{block_size}{at}")
    if n % 2:
        raise ValueError(
            f"int4 quantization needs an even row length{at}, got "
            f"shape {tuple(x.shape)}")
    if n % (2 * block_size):
        raise ValueError(
            f"block_size={block_size} does not tile the int4 halves "
            f"layout{at} (shape {tuple(x.shape)}): the row length "
            f"must be a multiple of 2 * block_size = {2 * block_size} "
            f"so each packed half holds whole scale blocks — pick a "
            f"smaller even block_size that divides {n // 2}")
    nb = n // block_size
    xb = x.astype(jnp.float32).reshape(rows, nb, block_size)
    amax = jnp.max(jnp.abs(xb), axis=2)
    scales = jnp.where(amax > 0.0, amax / _INT4_MAX, 1.0)
    v = jnp.clip(xb / scales[:, :, None], -_INT4_MAX, _INT4_MAX)
    q = jnp.clip(jnp.round(v), -_INT4_MAX, _INT4_MAX).astype(jnp.int8)
    return pack_int4(q.reshape(rows, n)), scales


def dequantize_rows_int4(
    packed: jnp.ndarray,
    scales: jnp.ndarray,
    block_size: int = 128,
    dtype: Any = jnp.float32,
) -> jnp.ndarray:
    """Inverse of :func:`quantize_rows_int4` (up to rounding error)."""
    return dequantize_rows(unpack_int4(packed), scales, block_size,
                           dtype)


def comm_residual_sizes(
    n: int, world: int, block_size: int
) -> Tuple[int, int]:
    """Per-device error-feedback buffer lengths for a
    :func:`quantized_psum` over an ``n``-element array on a
    ``world``-wide axis: ``(padded_total, shard)`` — the ``push``
    residual covers the locally quantized (padded) array, the ``pull``
    residual the re-quantized reduced shard this rank owns."""
    padded = n + (-n) % (world * block_size)
    return padded, padded // world


def hierarchical_residual_sizes(
    n: int, dcn: int, ici: int, block_size: int, ici_legs: bool = False
) -> dict:
    """Per-device error-feedback buffer lengths for ONE leaf of ``n``
    local elements through the hierarchical RS(ici) → AR(dcn) →
    AG(ici) reduce: ``push``/``pull`` compensate the DCN all-reduce's
    two quantization events (unchanged from the DCN-only design), and
    — with ``ici_legs`` — ``ici_push`` covers the full ici-padded
    local buffer quantized before the reduce-scatter while
    ``ici_pull`` covers the owned chunk quantized before the
    all-gather.  The ONE sizing shared by ``init_comm_state``,
    ``bucket_comm_state`` and the trace-time validation."""
    chunk = (n + (-n) % ici) // ici
    padded, shard = comm_residual_sizes(chunk, dcn, block_size)
    sizes = {"push": padded, "pull": shard}
    if ici_legs:
        sizes["ici_push"] = ici * chunk
        sizes["ici_pull"] = chunk
    return sizes


def zero3_residual_sizes(
    n: int, dcn: int, ici: int, block_size: int, ici_legs: bool = False
) -> dict:
    """Per-device error-feedback buffer lengths for ONE ZeRO-3 bucket of
    ``n`` local elements.  The bucket's gradient reduces as RS(ici) →
    AR(dcn) *into the shard* (no grad all-gather — the shard is where
    the update runs), and its PARAMETERS all-gather from the shard on
    use: ``push``/``pull`` compensate the DCN all-reduce of the owned
    chunk exactly as in :func:`hierarchical_residual_sizes`; with
    ``ici_legs``, ``ici_push`` covers the padded local grads quantized
    before the reduce-scatter and ``ag`` covers the param chunk
    quantized before the gather-on-use all-gather (the param-AG leg has
    no analog in the gradient path — it replaces the ZeRO-1 tail
    gather)."""
    chunk = (n + (-n) % ici) // ici
    padded, shard = comm_residual_sizes(chunk, dcn, block_size)
    sizes = {"push": padded, "pull": shard}
    if ici_legs:
        sizes["ici_push"] = ici * chunk
        sizes["ag"] = chunk
    return sizes


def init_residual(
    n: int, world: int, block_size: int = 256
) -> dict:
    """Zero error-feedback state for ONE flat array of ``n`` elements
    reduced over a ``world``-wide axis.  ``push`` compensates the
    first quantization (this rank's contribution), ``pull`` the
    second (the reduced shard this rank re-broadcasts)."""
    padded, shard = comm_residual_sizes(n, world, block_size)
    return {
        "push": jnp.zeros((padded,), jnp.float32),
        "pull": jnp.zeros((shard,), jnp.float32),
    }


def _rounding_key(
    cfg: CompressionConfig,
    axis_name,
    key: Optional[jnp.ndarray],
    step: Optional[jnp.ndarray],
) -> Optional[jnp.ndarray]:
    if cfg.rounding != "stochastic":
        return None
    if key is None:
        if step is None:
            # a constant key would re-roll the SAME dither every step,
            # turning "unbiased in expectation" into a fixed systematic
            # bias — refuse rather than silently degrade
            raise ValueError(
                "stochastic rounding needs per-step randomness: pass "
                "key= or thread comm state (its step counter derives "
                "one)"
            )
        key = jax.random.fold_in(jax.random.PRNGKey(0), step)
    return jax.random.fold_in(key, jax.lax.axis_index(axis_name))


def quantized_psum(
    x: jnp.ndarray,
    axis_name,
    compression: Union[str, CompressionConfig] = "int8",
    residual: Optional[dict] = None,
    key: Optional[jnp.ndarray] = None,
    step: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Optional[dict]]:
    """Approximate ``lax.psum(x, axis_name)`` with int8 bytes on wire.

    Three collectives replace the one full-width all-reduce, all over
    ``axis_name`` only (call this on the SLOW axis):

    1. each rank block-quantizes its (padded) array and ``all_to_all``s
       int8 values + fp32 scales — the reduce-scatter phase, 1 byte +
       4/block per element;
    2. each rank accumulates its shard from the received
       ``int8 x fp32-scale`` products (exact in fp32) — no bytes;
    3. the reduced shard is re-quantized and ``all_gather``-ed back,
       again 1 byte + 4/block per element.

    With ``residual`` (from :func:`init_residual`), both quantization
    events run with error feedback: the residual is added before
    quantizing and the new rounding error is returned as fresh state —
    pass it back next step.  Without it the call is stateless (and
    lossier over many steps).

    Non-finite inputs quantize to garbage (an inf amax zeroes the
    block): run overflow detection on the *inputs* (the loss-scaler
    consensus) and discard the returned residual for skipped steps.

    Returns ``(psum_approx, new_residual)`` — ``new_residual`` is None
    when ``residual`` is None; the output has ``x``'s shape and dtype.
    """
    cfg = as_compression_config(compression)
    world = _axis_size(axis_name)
    block = cfg.block_size
    shape, dtype, n = x.shape, x.dtype, int(jnp.size(x))
    padded, shard = comm_residual_sizes(n, world, block)

    flat = x.reshape(-1).astype(jnp.float32)
    if padded != n:
        flat = jnp.concatenate(
            [flat, jnp.zeros((padded - n,), jnp.float32)]
        )
    rkey = _rounding_key(cfg, axis_name, key, step)
    k1 = k2 = None
    if rkey is not None:
        k1, k2 = jax.random.split(rkey)

    if residual is not None:
        flat = flat + residual["push"]
    q, s = quantize_blockwise(flat, block, cfg.rounding, k1)
    new_residual = None
    if residual is not None:
        new_push = flat - dequantize_blockwise(q, s, block)

    # reduce-scatter phase: row r of the (world, shard) layout belongs
    # to rank r; exchange rows (and their scales) as int8/fp32
    qt = jax.lax.all_to_all(q.reshape(world, shard), axis_name, 0, 0)
    st = jax.lax.all_to_all(
        s.reshape(world, shard // block), axis_name, 0, 0
    )
    contrib = qt.astype(jnp.float32) * jnp.repeat(st, block, axis=1)
    y = jnp.sum(contrib, axis=0)

    if residual is not None:
        y = y + residual["pull"]
    q2, s2 = quantize_blockwise(y, block, cfg.rounding, k2)
    if residual is not None:
        new_pull = y - dequantize_blockwise(q2, s2, block)
        new_residual = {"push": new_push, "pull": new_pull}

    # invariant-typed gather (every rank receives identical bytes, so
    # the reconstruction is replicated over the axis)
    from apex_tpu.transformer.tensor_parallel.mappings import (
        all_gather_invariant,
    )

    gq = all_gather_invariant(q2, axis_name, axis=0, tiled=True)
    gs = all_gather_invariant(s2, axis_name, axis=0, tiled=True)
    out = dequantize_blockwise(gq, gs, block)[:n]
    return out.reshape(shape).astype(dtype), new_residual


def quantized_reduce_scatter(
    x: jnp.ndarray,
    axis_name,
    compression: Union[str, CompressionConfig] = "int8",
    residual: Optional[jnp.ndarray] = None,
    key: Optional[jnp.ndarray] = None,
    step: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Approximate ``lax.psum_scatter(x, axis_name, tiled=True)`` with
    int8 bytes on wire — the EQuARX ICI reduce-scatter leg.

    ``x`` is a flat ``(n,)`` fp32 array with ``n % world == 0``
    (callers pad to the ici extent exactly as the uncompressed path
    does).  Chunk boundaries are PRESERVED: rank *r* receives the sum
    of every rank's elements ``[r*n/world, (r+1)*n/world)`` — the
    per-row quantization (:func:`quantize_rows`) keeps blocks inside
    row boundaries for any chunk size, so turning compression on never
    moves a shard.  Each sender quantizes its whole (local) buffer
    once, ``all_to_all``s int8 values + fp32 scales, and the receiver
    accumulates exact ``int8 x fp32-scale`` products.

    ``residual`` is the flat ``(n,)`` ``ici_push`` error-feedback
    buffer (added before quantizing; the fresh rounding error comes
    back as ``new_residual``).  Returns ``(chunk (n/world,),
    new_residual_or_None)``."""
    cfg = as_compression_config(compression)
    world = _axis_size(axis_name)
    n = int(jnp.size(x))
    if n % world:
        raise ValueError(
            f"quantized_reduce_scatter needs size % world == 0 "
            f"(got {n} over {world}): pad like the uncompressed path"
        )
    shard = n // world
    flat = x.reshape(-1).astype(jnp.float32)
    rkey = _rounding_key(cfg, axis_name, key, step)
    if residual is not None:
        flat = flat + residual
    q, s = quantize_rows(
        flat.reshape(world, shard), cfg.block_size, cfg.rounding, rkey
    )
    new_residual = None
    if residual is not None:
        new_residual = flat - dequantize_rows(
            q, s, cfg.block_size
        ).reshape(-1)
    qt = jax.lax.all_to_all(q, axis_name, 0, 0)
    st = jax.lax.all_to_all(s, axis_name, 0, 0)
    chunk = jnp.sum(dequantize_rows(qt, st, cfg.block_size), axis=0)
    return chunk, new_residual


def quantized_all_gather(
    x: jnp.ndarray,
    axis_name,
    compression: Union[str, CompressionConfig] = "int8",
    residual: Optional[jnp.ndarray] = None,
    key: Optional[jnp.ndarray] = None,
    step: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Approximate a tiled ``all_gather(x, axis_name)`` with int8 bytes
    on wire — the EQuARX ICI all-gather leg.

    Each rank quantizes its ``(shard,)`` chunk once and gathers int8
    values + fp32 scales; every rank dequantizes the identical gathered
    bytes, so the result is replicated over the axis (invariant-typed,
    like the uncompressed ``all_gather_invariant`` it replaces).
    ``residual`` is the ``(shard,)`` ``ici_pull`` error-feedback
    buffer.  Returns ``(full (world*shard,), new_residual_or_None)``."""
    cfg = as_compression_config(compression)
    world = _axis_size(axis_name)
    shard = int(jnp.size(x))
    flat = x.reshape(-1).astype(jnp.float32)
    rkey = _rounding_key(cfg, axis_name, key, step)
    if residual is not None:
        flat = flat + residual
    q, s = quantize_blockwise(flat, cfg.block_size, cfg.rounding, rkey)
    new_residual = None
    if residual is not None:
        new_residual = flat - dequantize_blockwise(q, s, cfg.block_size)

    from apex_tpu.transformer.tensor_parallel.mappings import (
        all_gather_invariant,
    )

    gq = all_gather_invariant(q, axis_name, axis=0, tiled=True)
    gs = all_gather_invariant(s, axis_name, axis=0, tiled=True)
    nb = int(s.shape[0])
    out = dequantize_rows(
        gq.reshape(world, shard), gs.reshape(world, nb), cfg.block_size
    ).reshape(-1)
    return out, new_residual

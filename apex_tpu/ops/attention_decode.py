"""Decode-tier attention (fmha-decode): tiny-q against a paged KV cache.

The fourth rung of the measured attention ladder (short / mid / flash /
**decode** — docs/attention.md).  The first three rungs are built for
training shapes: s_q == s_k, both large, FLOP-bound.  Generation
inverts every one of those assumptions — s_q is 1 (or a small
speculative/chunked-prefill handful), s_k is the whole conversation so
far, and the arithmetic intensity collapses to ~2 FLOPs per KV byte, so
the kernel's job is to stream the cache at HBM bandwidth while the
elementwise chain (RoPE rotation, online-softmax bookkeeping, the
normalization tail) hides under the dots ("LLM Inference Acceleration
via Efficient Operation Fusion", PAPERS.md — the same fusion discipline
PRs 1/5/7 applied to training).

Why **paged**: a serving batch holds sequences of wildly different
lengths that grow, finish and get replaced mid-flight.  A dense
``(b, h, max_len, d)`` cache wastes HBM on every short sequence and
forces a copy whenever a slot is reused; a page pool
(``apex_tpu/serving/kv_cache.py``) allocates fixed-size token pages on
demand and maps each sequence's logical positions to physical pages
through a small int32 table.  The kernel consumes that layout directly:

- **pool layout** ``(num_pages, h, page_size, d)`` — one page holds
  ``page_size`` consecutive tokens of ONE sequence for ALL heads, so a
  single page DMA feeds every head's dot (the per-head trailing
  ``(page_size, d)`` tile is Mosaic-native);
- **scalar-prefetch page walk** — the grid is ``(b, h_blocks,
  num_logical_pages)`` and the k/v index maps read the page table from
  SMEM (``pltpu.PrefetchScalarGridSpec``), so the data-dependent gather
  is a DMA address computation, never a materialized ``take``;
- **head packing** (PR 1/PR 5's ``block_bh`` trick at decode shapes):
  all of a sequence's heads (grouped ``block_h`` at a time) ride one
  program and one page fetch, their tiny per-head dots issued
  back-to-back from one unrolled body so the pipeline never drains
  between (b, h) pairs — the s_q=1 grid that would otherwise idle the
  VPU stays saturated;
- **ONE kernel for fp32/bf16 and int8 pages**: int8 pools carry per
  ``(token, kv_block)`` fp32 scales (``ops/quantization.py``'s
  row-block machinery) and the kernel dequantizes each page in VMEM
  right before its dot — int8 halves (vs bf16) the bytes streamed, which
  is the whole game at decode intensity;
- **fused RoPE**: the query rotation for the current positions happens
  inside the kernel (``q*cos + rotate_half(q)*sin`` — the wrapper
  ships the pre-shuffled ``rotate_half(q)`` companion so the in-kernel
  work is pure elementwise multiply-add under the page stream; K is
  rotated once at cache-write time and never again);
- **partially-filled pages**: per-sequence ``lengths`` mask the tail
  page exactly, and logical pages past a sequence's length are skipped
  (``pl.when``) — unallocated table entries point at physical page 0,
  so the skipped DMA is always addressable.

Dispatch: serving callers hold a page table and call :func:`fmha_decode`
directly; ``flash_attention(implementation="decode")`` routes contiguous
``(b, h, s_k, d)`` K/V here by viewing it as trivially-paged storage
(``page_table[b] = b*pages + arange``) — the A/B seam
``tools/kernel_validation.py``'s ``validate_fmha_decode`` sweep times.
There is no auto-dispatch window: decode callers know they are decoding
(they hold a cache), and the training ladder's crossover measurements
stay untouched.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import _NEG_INF, _interpret
from apex_tpu.ops.common import shape_struct

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None

__all__ = [
    "fmha_decode",
    "paged_attention_reference",
    "decode_contiguous",
    "FMHA_DECODE_BLOCK_H",
    "FMHA_DECODE_MAX_ROWS",
]

_LANES = 128

#: How many heads one grid program packs (the decode analog of the
#: short/mid kernels' block_bh): each program holds block_h heads' q
#: resident and unrolls their per-page dots back-to-back over one page
#: DMA.  16 matches FMHA_SHORT_MAX_BLOCK_BH's measured code-size bound.
FMHA_DECODE_BLOCK_H = 16

#: VMEM-residency bound on the per-program query rows (block_h * sq):
#: the acc/m/l scratch buffers are (block_h*sq, d|128) fp32, so at the
#: chunked-prefill sq's (64/256) the s_q=1 head packing must shrink —
#: 512 rows keeps the three buffers under ~1 MB at d=128 while leaving
#: the s_q=1 default (block_h=16) untouched.
FMHA_DECODE_MAX_ROWS = 512


class _DecodeConfig(NamedTuple):
    """Static kernel configuration."""

    sm_scale: float
    causal: bool
    sq: int
    block_h: int
    page_size: int
    num_pages: int      # logical pages per sequence (grid extent)
    kv_block: int       # scale block width along d (int8 pages only)
    has_scales: bool
    has_rope: bool
    ancestor: Optional[tuple] = None  # (sq, sq) static tree mask rows


# ---------------------------------------------------------------------------
# XLA reference path (also the CPU fallback and the validation anchor)
# ---------------------------------------------------------------------------


def _dequant_pages(pages, scales, kv_block):
    """(num_pages, h, page_size, d) int8 + (num_pages, h, page_size, nb)
    fp32 scales -> fp32, per-(token, kv_block) dequantization."""
    d = pages.shape[-1]
    expand = jnp.repeat(scales, kv_block, axis=-1)[..., :d]
    return pages.astype(jnp.float32) * expand


def paged_attention_reference(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    k_scales: Optional[jnp.ndarray] = None,
    v_scales: Optional[jnp.ndarray] = None,
    kv_block: int = _LANES,
    ancestor: Optional[tuple] = None,
) -> jnp.ndarray:
    """Plain-XLA paged decode attention — the correctness reference.

    Materializes the per-sequence gather (``take`` over the page table)
    and computes masked softmax attention in fp32.  Query token ``i`` of
    sequence ``b`` sits at position ``lengths[b] - sq + i`` and attends
    to cache positions ``<= `` its own (``causal=True``) or to all
    ``lengths[b]`` positions.  The cache is expected to already contain
    the query tokens' own K/V (write-before-attend, so a decode token
    attends to itself).

    ``ancestor`` replaces the in-window causal triangle with a static
    (sq, sq) boolean matrix over the FRESH rows (cache positions
    ``lengths[b] - sq + j``): query row ``i`` attends fresh row ``j``
    iff ``ancestor[i][j]`` — tree speculation's per-branch visibility.
    The committed prefix (positions ``< lengths[b] - sq``) stays fully
    visible to every row.
    """
    b, h, sq, d = q.shape
    num_pages = page_table.shape[1]
    page_size = k_pages.shape[2]
    scale = (1.0 / d**0.5) if sm_scale is None else float(sm_scale)

    def gather(pages, scales):
        x = jnp.take(pages, page_table, axis=0)  # (b, np, h, ps, d)
        if scales is not None:
            s = jnp.take(scales, page_table, axis=0)
            x = _dequant_pages(x, s, kv_block)
        x = jnp.moveaxis(x, 2, 1)
        return x.reshape(b, h, num_pages * page_size, d)

    k = gather(k_pages, k_scales)
    v = gather(v_pages, v_scales)
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    k_pos = jnp.arange(num_pages * page_size)[None, None, None, :]
    if ancestor is not None:
        amat = jnp.asarray(ancestor, dtype=bool)       # (sq, sq)
        fresh = k_pos - (lengths[:, None, None, None] - sq)
        in_window = (fresh >= 0) & (fresh < sq)
        q_i = jnp.arange(sq)[None, None, :, None]
        tree = amat[q_i, jnp.clip(fresh, 0, sq - 1)]
        mask = (fresh < 0) | (in_window & tree)
    elif causal:
        q_pos = (lengths[:, None, None, None] - sq
                 + jnp.arange(sq)[None, None, :, None])
        mask = k_pos <= q_pos
    else:
        mask = k_pos < lengths[:, None, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask, p, 0.0)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _decode_kernel(*refs, cfg: _DecodeConfig):
    pt_ref, len_ref = refs[:2]
    rest = list(refs[2:])
    q_ref = rest.pop(0)
    qrot_ref = cos_ref = sin_ref = None
    if cfg.has_rope:
        qrot_ref, cos_ref, sin_ref = rest.pop(0), rest.pop(0), rest.pop(0)
    k_ref, v_ref = rest.pop(0), rest.pop(0)
    ks_ref = vs_ref = None
    if cfg.has_scales:
        ks_ref, vs_ref = rest.pop(0), rest.pop(0)
    o_ref, acc_ref, m_ref, l_ref = rest

    b, hb, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    sq, ps = cfg.sq, cfg.page_size
    ln = len_ref[b]

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # logical pages at or past this sequence's length hold nothing this
    # query may attend to — skip their compute entirely (the decode
    # analog of the mid kernel's causal block-skip; with variable
    # lengths in a batch the grid covers the longest sequence and short
    # ones skip the difference)
    @pl.when(p * ps < ln)
    def _body():
        d = q_ref.shape[-1]
        for hi in range(cfg.block_h):
            qh = q_ref[0, hi].astype(jnp.float32)            # (sq, d)
            if cfg.has_rope:
                # q*cos + rotate_half(q)*sin: the rotation's FLOPs run
                # in-kernel under the page stream; the half-swap data
                # shuffle happened once in the wrapper (XLA fuses it
                # into the q projection epilogue)
                qh = (qh * cos_ref[0, hi].astype(jnp.float32)
                      + qrot_ref[0, hi].astype(jnp.float32)
                      * sin_ref[0, hi].astype(jnp.float32))
            qh = qh * cfg.sm_scale
            kh = k_ref[0, hi].astype(jnp.float32)            # (ps, d)
            vh = v_ref[0, hi].astype(jnp.float32)
            if cfg.has_scales:
                kh = kh * jnp.repeat(
                    ks_ref[0, hi], cfg.kv_block, axis=1)[:, :d]
                vh = vh * jnp.repeat(
                    vs_ref[0, hi], cfg.kv_block, axis=1)[:, :d]
            s = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                                 # (sq, ps)
            k_pos = p * ps + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            if cfg.ancestor is not None:
                # tree verify: the last sq cache slots are the
                # candidate rows; row i sees fresh slot j iff the
                # STATIC ancestor matrix says so, plus the whole
                # committed prefix.  Each row's allowed-column set is
                # packed into an int32 bitmask selected by row iota
                # (Pallas kernels cannot capture constant arrays), so
                # the mask is sq scalar selects + one variable shift —
                # VPU work that hides under the page DMA.
                fresh = k_pos - (ln - sq)
                row = jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 0)
                bits = jnp.zeros_like(row)
                for i in range(sq):
                    rb = sum(int(cfg.ancestor[i][j]) << j
                             for j in range(sq))
                    bits = jnp.where(row == i, rb, bits)
                fr = jnp.clip(fresh, 0, sq - 1)
                tree = (jnp.right_shift(bits, fr) & 1) == 1
                mask = (fresh < 0) | (
                    (fresh >= 0) & (fresh < sq) & tree)
            elif cfg.causal:
                q_pos = ln - sq + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 0)
                mask = k_pos <= q_pos
            else:
                mask = k_pos < ln
            s = jnp.where(mask, s, _NEG_INF)
            r0, r1 = hi * sq, (hi + 1) * sq
            m_prev = m_ref[r0:r1, 0:1]
            l_prev = l_ref[r0:r1, 0:1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            pexp = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(pexp, axis=-1, keepdims=True)
            acc_ref[r0:r1] = acc_ref[r0:r1] * corr + jax.lax.dot_general(
                pexp, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[r0:r1] = jnp.broadcast_to(m_new, (sq, m_ref.shape[1]))
            l_ref[r0:r1] = jnp.broadcast_to(l_new, (sq, l_ref.shape[1]))

    @pl.when(p == cfg.num_pages - 1)
    def _finalize():
        # the softmax-normalization tail, fused (the operation-fusion
        # paper's point: this divide never round-trips through HBM).
        # A zero-length sequence (an idle serving slot) clamps l and
        # writes garbage the caller masks.
        ll = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[...] / ll).reshape(o_ref.shape[1:]).astype(
            o_ref.dtype)


def _decode_pallas(q, q_rot, cos, sin, k_pages, v_pages, k_scales,
                   v_scales, page_table, lengths, cfg: _DecodeConfig):
    b, h, sq, d = q.shape
    ps = cfg.page_size
    nb = k_scales.shape[-1] if cfg.has_scales else 0
    bh = cfg.block_h
    n_hb = h // bh

    def qmap(bb, hb, p, pt, ln):
        return (bb, hb, 0, 0)

    def kvmap(bb, hb, p, pt, ln):
        return (pt[bb, p], hb, 0, 0)

    in_specs = [pl.BlockSpec((1, bh, sq, d), qmap)]
    inputs = [q]
    if cfg.has_rope:
        in_specs += [pl.BlockSpec((1, bh, sq, d), qmap)] * 3
        inputs += [q_rot, cos, sin]
    in_specs += [
        pl.BlockSpec((1, bh, ps, d), kvmap),
        pl.BlockSpec((1, bh, ps, d), kvmap),
    ]
    inputs += [k_pages, v_pages]
    if cfg.has_scales:
        in_specs += [
            pl.BlockSpec((1, bh, ps, nb), kvmap),
            pl.BlockSpec((1, bh, ps, nb), kvmap),
        ]
        inputs += [k_scales, v_scales]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_hb, cfg.num_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bh, sq, d), qmap),
        scratch_shapes=[
            pltpu.VMEM((bh * sq, d), jnp.float32),
            pltpu.VMEM((bh * sq, _LANES), jnp.float32),
            pltpu.VMEM((bh * sq, _LANES), jnp.float32),
        ],
    )
    from apex_tpu.ops.common import tpu_compiler_params

    return pl.pallas_call(
        functools.partial(_decode_kernel, cfg=cfg),
        grid_spec=grid_spec,
        out_shape=shape_struct((b, h, sq, d), q.dtype, q, k_pages,
                               v_pages),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32), *inputs)


# ---------------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------------


def _rotate_half(x):
    d = x.shape[-1]
    return jnp.concatenate([-x[..., d // 2:], x[..., : d // 2]], axis=-1)


def _rope_operands(q, rope: Tuple[jnp.ndarray, jnp.ndarray]):
    """Expand (cos, sin) half-tables to full-width per-(b, h, sq) planes
    plus the rotate_half(q) companion the kernel's elementwise form
    needs.  ``rope`` is ``(cos, sin)`` of shape ``(b, sq, d/2)`` (the
    per-sequence decode positions, ``ops/rope.py rope_cos_sin``)."""
    b, h, sq, d = q.shape
    cos, sin = rope
    if cos.shape != (b, sq, d // 2):
        raise ValueError(
            f"rope tables must be (b, sq, d/2) = ({b}, {sq}, {d // 2}), "
            f"got {cos.shape}"
        )
    full = lambda t: jnp.broadcast_to(
        jnp.concatenate([t, t], axis=-1)[:, None], (b, h, sq, d)
    ).astype(jnp.float32)
    return _rotate_half(q.astype(jnp.float32)), full(cos), full(sin)


def _pick_block_h(h: int, sq: int = 1) -> int:
    """Largest head packing that divides ``h``, capped by the code-size
    bound AND the VMEM row budget (``block_h * sq <=
    FMHA_DECODE_MAX_ROWS``): a chunked-prefill ``sq`` of 256 packs
    fewer heads per program than the s_q=1 decode default so the
    fp32 accumulator scratch stays resident."""
    bh = max(1, min(h, FMHA_DECODE_BLOCK_H,
                    FMHA_DECODE_MAX_ROWS // max(sq, 1)))
    while h % bh:
        bh -= 1
    return bh


def fmha_decode(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    k_scales: Optional[jnp.ndarray] = None,
    v_scales: Optional[jnp.ndarray] = None,
    kv_block: int = _LANES,
    rope: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    block_h: Optional[int] = None,
    implementation: Optional[str] = None,
    ancestor: Optional[tuple] = None,
) -> jnp.ndarray:
    """Decode attention: ``q (b, h, sq, d)`` against a paged KV cache.

    ``k_pages``/``v_pages`` are the ``(num_pages, h, page_size, d)``
    pool (fp32/bf16, or int8 with ``k_scales``/``v_scales`` per-
    ``(token, kv_block)`` fp32 scales of shape ``(num_pages, h,
    page_size, ceil(d/kv_block))`` — ``serving/kv_cache.py`` writes
    both layouts).  ``page_table (b, logical_pages)`` maps each
    sequence's logical page to a physical pool page (unallocated
    entries MUST hold a valid index — the allocator's reserved null
    page 0); ``lengths (b,)`` counts valid tokens per sequence
    INCLUDING the query tokens (write-before-attend: a decode token
    attends to itself).

    ``sq`` is 1 for plain decode; small ``sq > 1`` serves speculative
    verification and chunked prefill, with ``causal=True`` masking each
    query token at its own position ``lengths[b] - sq + i``.  ``rope``
    fuses the query-side rotation for those positions into the kernel
    (K is rotated at cache-write time).  Forward-only by design — the
    generation loop never differentiates through the cache.

    ``implementation``: None = platform default (Pallas on TPU, XLA
    reference otherwise), ``"pallas"`` strict, ``"xla"`` reference.

    ``ancestor`` (static (sq, sq) rows of 0/1, lower-triangular with a
    unit diagonal) switches the in-window causal triangle to TREE
    visibility: query row ``i`` attends candidate row ``j`` iff
    ``ancestor[i][j]`` — several speculative branches verified against
    one committed prefix in one cache pass.  Requires ``causal=True``
    (the committed prefix stays fully visible either way).
    """
    if (k_scales is None) != (v_scales is None):
        raise ValueError("int8 pages need BOTH k_scales and v_scales")
    if k_pages.dtype == jnp.int8 and k_scales is None:
        raise ValueError("int8 pages require k_scales/v_scales")
    if k_pages.dtype != jnp.int8 and k_scales is not None:
        raise ValueError(
            f"scales passed with {k_pages.dtype} pages — scales belong "
            "to int8 pools only (stale scales would silently rescale "
            "full-precision K/V)")
    if q.shape[1] != k_pages.shape[1]:
        raise ValueError(
            f"q heads {q.shape[1]} != pool heads {k_pages.shape[1]}"
        )
    if q.shape[-1] != k_pages.shape[-1]:
        raise ValueError(
            f"q head_dim {q.shape[-1]} != pool head_dim "
            f"{k_pages.shape[-1]}"
        )
    if page_table.ndim != 2 or page_table.shape[0] != q.shape[0]:
        raise ValueError(
            f"page_table must be (batch, logical_pages), got "
            f"{page_table.shape} for batch {q.shape[0]}"
        )
    b, h, sq, d = q.shape
    if block_h is not None and h % int(block_h):
        raise ValueError(f"block_h {block_h} must divide heads {h}")
    if rope is not None and rope[0].shape != (b, sq, d // 2):
        raise ValueError(
            f"rope tables must be (b, sq, d/2) = ({b}, {sq}, {d // 2}), "
            f"got {rope[0].shape}"
        )
    scale = (1.0 / d**0.5) if sm_scale is None else float(sm_scale)

    if ancestor is not None:
        if not causal:
            raise ValueError(
                "ancestor mask requires causal=True — tree rows refine "
                "the causal window, they do not replace the length mask")
        ancestor = tuple(
            tuple(bool(x) for x in row) for row in ancestor)
        if len(ancestor) != sq or any(len(r) != sq for r in ancestor):
            raise ValueError(
                f"ancestor must be ({sq}, {sq}) to match s_q, got "
                f"({len(ancestor)}, "
                f"{len(ancestor[0]) if ancestor else 0})")
        if sq > 31:
            raise ValueError(
                f"ancestor s_q {sq} > 31 — the kernel packs each "
                "row's visibility into an int32 bitmask; speculative "
                "trees are a small handful of rows by design")
        for i, row in enumerate(ancestor):
            if not row[i]:
                raise ValueError(
                    f"ancestor diagonal must be 1 (row {i} attends "
                    "itself — write-before-attend)")
            if any(row[i + 1:]):
                raise ValueError(
                    f"ancestor row {i} attends a later row — the tree "
                    "must be topologically ordered (lower-triangular)")

    from apex_tpu.ops.common import KernelLoweringError, run_kernel
    from apex_tpu.utils.platform import default_implementation

    if implementation not in (None, "pallas", "xla", "decode"):
        raise ValueError(
            f"unknown implementation {implementation!r}; expected None, "
            "'pallas'/'decode', or 'xla'"
        )
    if implementation == "decode":
        implementation = "pallas"
    if pl is None and implementation == "pallas":
        raise KernelLoweringError(
            "implementation='pallas' requested but Pallas failed to import"
        )
    impl = implementation or default_implementation()
    if pl is None:
        impl = "xla"

    def _xla_path():
        qq = q
        if rope is not None:
            from apex_tpu.ops.rope import apply_rope_tables

            qq = apply_rope_tables(q, rope[0][:, None], rope[1][:, None])
        return paged_attention_reference(
            qq, k_pages, v_pages, page_table, lengths, causal=causal,
            sm_scale=scale, k_scales=k_scales, v_scales=v_scales,
            kv_block=kv_block, ancestor=ancestor,
        )

    def _pallas_path():
        bh = _pick_block_h(h, sq) if block_h is None else int(block_h)
        if h % bh:
            raise ValueError(f"block_h {bh} must divide heads {h}")
        if bh * sq > FMHA_DECODE_MAX_ROWS:
            # the per-program fp32 scratch is (block_h*sq) rows — past
            # the budget even block_h=1 cannot honor it, and lowering
            # failures at serve time are opaque.  Decode s_q is "1 or
            # a small chunk" by design; bigger tiles belong to the
            # training ladder (or implementation="xla").
            raise ValueError(
                f"block_h*sq = {bh}*{sq} exceeds the decode kernel's "
                f"per-program row budget (FMHA_DECODE_MAX_ROWS="
                f"{FMHA_DECODE_MAX_ROWS}); chunk the query (sq <= "
                f"{FMHA_DECODE_MAX_ROWS}) or use implementation='xla'")
        cfg = _DecodeConfig(
            sm_scale=scale, causal=causal, sq=sq, block_h=bh,
            page_size=k_pages.shape[2], num_pages=page_table.shape[1],
            kv_block=int(kv_block), has_scales=k_scales is not None,
            has_rope=rope is not None, ancestor=ancestor,
        )
        q_rot = cos = sin = None
        if rope is not None:
            q_rot, cos, sin = _rope_operands(q, rope)
        return _decode_pallas(
            q, q_rot, cos, sin, k_pages, v_pages, k_scales, v_scales,
            page_table, lengths, cfg,
        )

    return run_kernel(
        "fmha_decode", _pallas_path, _xla_path, implementation, impl
    )


def decode_contiguous(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    page_size: int = 128,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """Run :func:`fmha_decode` over CONTIGUOUS ``(b, h, s_k, d)`` K/V by
    viewing it as trivially-paged storage — the
    ``flash_attention(implementation="decode")`` seam, and the A/B
    comparator ``validate_fmha_decode`` times against the XLA reference.

    ``causal=True`` requires ``sq <= sk`` and places query token ``i``
    at position ``sk - sq + i`` (the decode convention: the cache's
    tail IS the query window — for ``sq == sk`` this is exactly the
    training ladder's causal mask).
    """
    b, h, sk, d = k.shape
    sq = q.shape[2]
    if causal and sq > sk:
        raise ValueError(
            f"decode causal needs sq <= sk (query positions are the "
            f"cache tail), got sq={sq} sk={sk}"
        )
    ps = min(page_size, sk)
    pad = (-sk) % ps
    if pad:
        padw = ((0, 0), (0, 0), (0, pad), (0, 0))
        k, v = jnp.pad(k, padw), jnp.pad(v, padw)
    num_pages = (sk + pad) // ps
    # (b, h, np*ps, d) -> (b*np, h, ps, d): sequence b's logical page p
    # is physical page b*np + p
    pagify = lambda x: jnp.moveaxis(
        x.reshape(b, h, num_pages, ps, d), 2, 1
    ).reshape(b * num_pages, h, ps, d)
    page_table = (
        jnp.arange(b, dtype=jnp.int32)[:, None] * num_pages
        + jnp.arange(num_pages, dtype=jnp.int32)[None, :]
    )
    lengths = jnp.full((b,), sk, jnp.int32)
    return fmha_decode(
        q, pagify(k), pagify(v), page_table, lengths, causal=causal,
        sm_scale=sm_scale, implementation=implementation,
    )

"""Fused scale + mask + softmax kernels.

Capability match for the reference's Megatron softmax extensions
``scaled_masked_softmax_cuda`` and ``scaled_upper_triang_masked_softmax_cuda``
(reference: csrc/megatron/scaled_masked_softmax.h,
csrc/megatron/scaled_upper_triang_masked_softmax.h, python dispatch at
apex/transformer/functional/fused_softmax.py:21-199), re-designed for TPU:

- softmax statistics always in fp32 (the kernels' accumulation contract),
- one ``custom_vjp`` shared by the Pallas TPU kernel and the XLA fallback,
  with the fused backward ``dx = scale * y * (dy - sum(dy * y))`` the CUDA
  backward kernels compute in one pass,
- masking semantics match the reference: mask entries that are *True* are
  masked **out** (filled with -10000 before softmax), and the causal
  variant masks the strict upper triangle.

Unlike the CUDA kernels there is no shape eligibility window
(16 < sk <= 2048, sq % 4 == 0, ...): the Pallas kernel tiles any shape and
the XLA path handles the rest, so ``is_kernel_available`` is about
platform, not shape.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.common import run_kernel, shape_struct

__all__ = [
    "scaled_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
]

_MASK_FILL = -10000.0


# ---------------------------------------------------------------------------
# Pallas forward kernel (causal / unmasked; rows tiled into VMEM)
# ---------------------------------------------------------------------------


def _softmax_fwd_kernel(x_ref, o_ref, *, scale, causal, block_q):
    """One (1, block_q, sk) tile: scale, optional causal mask, softmax.

    Rows are query positions; the causal mask for global query row ``q``
    keeps keys ``k <= q``, matching the reference's upper-triangular fill
    (reference: csrc/megatron/scaled_upper_triang_masked_softmax.h).
    """
    j = pl.program_id(1)
    x = x_ref[0].astype(jnp.float32) * scale  # (block_q, sk)
    if causal:
        q_idx = j * block_q + jax.lax.broadcasted_iota(
            jnp.int32, x.shape, 0
        )
        k_idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(k_idx > q_idx, _MASK_FILL, x)
    x = x - jnp.max(x, axis=-1, keepdims=True)
    ex = jnp.exp(x)
    o_ref[0] = (ex / jnp.sum(ex, axis=-1, keepdims=True)).astype(o_ref.dtype)


try:  # imported lazily on CPU-only hosts that lack Mosaic
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None


def _interpret() -> bool:
    """Run Pallas kernels in interpreter mode off-TPU so the kernel body
    is still exercised (and testable) on CPU."""
    from apex_tpu.utils.platform import is_tpu

    return not is_tpu()


def _softmax_fwd_pallas(x3d: jnp.ndarray, scale: float, causal: bool):
    m, sq, sk = x3d.shape
    block_q = max(8, min(256, sq))
    pad = (-sq) % block_q
    if pad:
        x3d = jnp.pad(x3d, ((0, 0), (0, pad), (0, 0)))
    padded_sq = sq + pad
    grid = (m, padded_sq // block_q)
    out = pl.pallas_call(
        functools.partial(
            _softmax_fwd_kernel, scale=scale, causal=causal, block_q=block_q
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, block_q, sk), lambda i, j: (i, j, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, sk), lambda i, j: (i, j, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=shape_struct((m, padded_sq, sk), x3d.dtype, x3d),
        interpret=_interpret(),
    )(x3d)
    if pad:
        out = out[:, :sq]
    return out


def _softmax_fwd_xla(
    x3d: jnp.ndarray,
    scale: float,
    causal: bool,
    mask: Optional[jnp.ndarray],
):
    x = x3d.astype(jnp.float32) * scale
    if causal:
        sq, sk = x.shape[-2:]
        q_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        x = jnp.where(k_idx > q_idx, _MASK_FILL, x)
    if mask is not None:
        x = jnp.where(mask, _MASK_FILL, x)
    x = x - jnp.max(x, axis=-1, keepdims=True)
    ex = jnp.exp(x)
    return (ex / jnp.sum(ex, axis=-1, keepdims=True)).astype(x3d.dtype)


def _softmax_fwd(x3d, mask, scale, causal, implementation):
    from apex_tpu.ops.common import KernelLoweringError

    if pl is None and implementation == "pallas":
        raise KernelLoweringError(
            "implementation='pallas' requested but Pallas failed to import"
        )
    if implementation == "pallas" and mask is not None:
        # no pallas kernel exists for the arbitrary-mask variant — honor
        # the no-silent-degradation contract by saying so loudly
        raise KernelLoweringError(
            "the masked softmax variant has no Pallas kernel (mask fusion "
            "is already optimal in XLA, and the in-kernel masked fast "
            "path is flash attention's segment-id/bias support); use "
            "implementation='xla' or drop the explicit request"
        )
    # Auto mode routes to XLA *by measurement*: standalone softmax is
    # bandwidth-bound and XLA's fused max/exp/sum pipeline beats the
    # Pallas tile kernel by ~1.3x on v5e (see KERNELS_TPU.json).  The
    # kernel stays available via implementation='pallas' for the
    # cross-check tier; the fast path that matters for attention is the
    # flash kernel, which supersedes this op entirely.
    impl = implementation or "xla"
    if mask is not None or pl is None:
        # the padded-mask variant is XLA-only by design: XLA fuses the
        # mask+softmax chain optimally, and the arbitrary-mask fast path
        # in this library is the flash-attention kernel's segment-id /
        # bias support, not this op
        impl = "xla"
    return run_kernel(
        "scaled_softmax",
        lambda: _softmax_fwd_pallas(x3d, scale, causal),
        lambda: _softmax_fwd_xla(x3d, scale, causal, mask),
        implementation if mask is None else None,
        impl,
    )


# ---------------------------------------------------------------------------
# custom_vjp core.  mask is a (differentiation-constant) positional arg so
# one vjp serves the causal, padded and unmasked variants.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _fused_softmax(x3d, mask, scale: float, causal: bool,
                   implementation: Optional[str]):
    return _softmax_fwd(x3d, mask, scale, causal, implementation)


def _fused_softmax_fwd(x3d, mask, scale, causal, implementation):
    y = _softmax_fwd(x3d, mask, scale, causal, implementation)
    return y, y


def _fused_softmax_bwd(scale, causal, implementation, y, dy):
    """Fused softmax backward: ``dx = scale * y * (dy - sum(dy*y))``
    (reference: csrc/megatron/scaled_masked_softmax.h backward kernel)."""
    yf = y.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    inner = jnp.sum(dyf * yf, axis=-1, keepdims=True)
    dx = (scale * yf * (dyf - inner)).astype(y.dtype)
    return (dx, None)


_fused_softmax.defvjp(_fused_softmax_fwd, _fused_softmax_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _as_3d(x: jnp.ndarray):
    sq, sk = x.shape[-2:]
    return x.reshape(-1, sq, sk)


def scaled_softmax(
    x: jnp.ndarray,
    scale: float = 1.0,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """``softmax(scale * x)`` over the last dim, fp32 internals
    (reference: ``scaled_softmax_cuda`` path of
    apex/transformer/functional/fused_softmax.py:98-112)."""
    shape = x.shape
    return _fused_softmax(
        _as_3d(x), None, float(scale), False, implementation
    ).reshape(shape)


def scaled_masked_softmax(
    x: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    scale: float = 1.0,
    causal: bool = False,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """``softmax(scale * x + mask_fill)`` where True mask entries are
    masked out (reference: ``ScaledMaskedSoftmax``,
    apex/transformer/functional/fused_softmax.py:67-95).

    ``x`` is (..., sq, sk); ``mask`` broadcasts against ``x`` (the
    reference uses (b, 1, sq, sk) against (b, np, sq, sk)).
    ``causal=True`` additionally masks the strict upper triangle — the
    composition the reference cannot express in one kernel.
    """
    if mask is None:
        if causal:
            return scaled_upper_triang_masked_softmax(
                x, scale, implementation
            )
        return scaled_softmax(x, scale, implementation)
    shape = x.shape
    mask_b = jnp.broadcast_to(mask, shape).reshape(-1, *shape[-2:])
    return _fused_softmax(
        _as_3d(x), mask_b, float(scale), causal, implementation
    ).reshape(shape)


def scaled_upper_triang_masked_softmax(
    x: jnp.ndarray,
    scale: float = 1.0,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """Causal ``softmax(scale * x)`` masking the strict upper triangle
    (reference: ``ScaledUpperTriangMaskedSoftmax``,
    apex/transformer/functional/fused_softmax.py:21-49)."""
    shape = x.shape
    return _fused_softmax(
        _as_3d(x), None, float(scale), True, implementation
    ).reshape(shape)

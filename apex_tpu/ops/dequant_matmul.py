"""Weight-dequantizing matmul: stream int8/int4 weights, dequantize in
VMEM, never materialize the wide matrix in HBM.

Decode at small batch is weight-streaming-bound: every generated token
reads every matmul weight of the model once, so the projection/FFN dots
run at HBM bandwidth and their cost is simply *bytes of weights*.  The
paged-attention kernel already streams its K/V pool as int8 and
rescales per block inside the tile (``attention_decode._decode_kernel``
— ``kh * repeat(ks, kv_block)`` right before the dot); this module
lifts exactly that pattern to the QKV / output-projection / FFN dots:

- weights live in HBM as block-wise int8 (:func:`quantize_rows`) or
  packed int4 (:func:`quantize_rows_int4` — two nibbles per byte,
  halves layout, per-block fp32 scales);
- each kernel program DMAs ONE narrow weight tile into VMEM,
  dequantizes it there (``q * repeat(scales, block)``, plus the
  shift-free nibble sign-extend for int4) and feeds the MXU;
- the fp32/bf16 weight never exists anywhere — not in HBM, not as a
  whole in VMEM — so the decode roofline drops to 1/4 (int8) or 1/8
  (int4) of the fp32 byte stream, and the same drop applies to the
  largest model a chip can SERVE (tools/memory_audit.py --serve).

The XLA fallback is the literal dequantize-then-dot (the reference the
kernel-validation gate compares against): same math, but it
materializes the wide matrix as an XLA temp.  Dispatch follows the
package's kernel contract (:func:`apex_tpu.ops.common.run_kernel`):
auto mode falls back with a logged warning, explicit
``implementation="pallas"`` raises on lowering failure.

Layout contract (what the tiling assumes, validated loudly):

- int8: ``qweight (k, n) int8``, ``scales (k, n / block) fp32`` —
  blocks along the OUTPUT features, whole blocks only (the
  ``quantize_rows(leaf=...)`` strict mode enforces this at the
  weight-pool seam);
- int4: ``qweight (k, n / 2) int8`` packed bytes (:func:`pack_int4`'s
  halves layout: low nibble = output column ``c``, high nibble =
  column ``c + n/2``), ``scales (k, n / block) fp32``, ``n`` a
  multiple of ``2 * block`` so each half holds whole scale blocks.
  The kernel writes a ``(2, m, n/2)`` output — one slab per nibble
  half — and the wrapper concatenates them back to ``(m, n)``, so no
  lane-dim interleave ever happens on device.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops.attention import _interpret
from apex_tpu.ops.common import run_kernel, shape_struct, tpu_compiler_params
from apex_tpu.ops.quantization import (
    dequantize_rows,
    quantize_rows,
    quantize_rows_int4,
    unpack_int4,
)
from apex_tpu.utils.platform import default_implementation

__all__ = [
    "dequant_matmul",
    "dequant_matmul_reference",
    "quantize_weight",
    "dequantize_weight",
    "weight_pool_dtype",
    "weight_pool_block",
]

#: per-program f32 dequant-tile budget (elements): bounds the widest
#: output tile so k x bn x 4 bytes of dequantized weight stays well
#: under the ~16 MB VMEM core budget next to x, the int tile and the
#: accumulator
_TILE_ELEMS = 1 << 20


def _pick_bn(n: int, bs: int, k: int) -> int:
    """Output-tile width: the largest multiple of ``bs`` that divides
    ``n`` and keeps the dequantized f32 tile under the VMEM budget
    (floor: one scale block per program)."""
    cap = max(bs, (_TILE_ELEMS // max(k, 1)) // bs * bs)
    bn = bs
    m = n // bs
    for t in range(1, m + 1):
        w = t * bs
        if w > cap:
            break
        if n % w == 0:
            bn = w
    return bn


# ------------------------------------------------------------ kernels
def _int8_kernel(x_ref, w_ref, s_ref, o_ref, *, block_size):
    xi = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    w = w * jnp.repeat(s_ref[...], block_size, axis=1)
    o_ref[...] = jax.lax.dot_general(
        xi, w, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _int4_kernel(x_ref, p_ref, s_ref, o_ref, *, block_size):
    xi = x_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.int32) & 0xFF
    lo = (((p & 0xF) ^ 8) - 8).astype(jnp.float32)
    hi = ((((p >> 4) & 0xF) ^ 8) - 8).astype(jnp.float32)
    s = s_ref[...]                       # (k, 2, nb_tile)
    dot = lambda a, b: jax.lax.dot_general(
        a, b, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = dot(xi, lo * jnp.repeat(s[:, 0], block_size, axis=1))
    o_ref[1] = dot(xi, hi * jnp.repeat(s[:, 1], block_size, axis=1))


def _int8_pallas(x, qw, scales, block_size):
    m, k = x.shape
    _, n = qw.shape
    bn = _pick_bn(n, block_size, k)
    nbt = bn // block_size
    out = pl.pallas_call(
        functools.partial(_int8_kernel, block_size=block_size),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((m, k), lambda j: (0, 0)),
            pl.BlockSpec((k, bn), lambda j: (0, j)),
            pl.BlockSpec((k, nbt), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda j: (0, j)),
        out_shape=shape_struct((m, n), jnp.float32, x, qw, scales),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",)
        ),
        interpret=_interpret(),
    )(x, qw, scales)
    return out.astype(x.dtype)


def _int4_pallas(x, qp, scales, block_size):
    m, k = x.shape
    _, n2 = qp.shape
    nb = scales.shape[1]
    bn = _pick_bn(n2, block_size, k)
    nbt = bn // block_size
    s3 = scales.reshape(k, 2, nb // 2)
    out = pl.pallas_call(
        functools.partial(_int4_kernel, block_size=block_size),
        grid=(n2 // bn,),
        in_specs=[
            pl.BlockSpec((m, k), lambda j: (0, 0)),
            pl.BlockSpec((k, bn), lambda j: (0, j)),
            pl.BlockSpec((k, 2, nbt), lambda j: (0, 0, j)),
        ],
        out_specs=pl.BlockSpec((2, m, bn), lambda j: (0, 0, j)),
        out_shape=shape_struct((2, m, n2), jnp.float32, x, qp, scales),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",)
        ),
        interpret=_interpret(),
    )(x, qp, s3)
    # the halves layout: slab 0 = output columns [0, n/2), slab 1 =
    # [n/2, n) — one concat restores the original order
    return jnp.concatenate([out[0], out[1]], axis=-1).astype(x.dtype)


# ----------------------------------------------------------- XLA path
def dequant_matmul_reference(x, qweight, scales, *, weight_dtype,
                             block_size):
    """The dequantize-then-dot reference: materialize the wide matrix
    (as an XLA temp) and run a plain dot — the baseline the
    never-lose-to-XLA kernel-validation gate compares against, and the
    auto-mode fallback off-TPU."""
    if weight_dtype == "int8":
        w = dequantize_rows(qweight, scales, block_size)
    else:
        w = dequantize_rows(unpack_int4(qweight), scales, block_size)
    out = jax.lax.dot_general(
        x.astype(jnp.float32), w,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out.astype(x.dtype)


# ------------------------------------------------------- public entry
def dequant_matmul(
    x: jnp.ndarray,
    qweight: jnp.ndarray,
    scales: jnp.ndarray,
    *,
    weight_dtype: str,
    block_size: Optional[int] = None,
    implementation: Optional[str] = None,
) -> jnp.ndarray:
    """``x @ W`` where ``W`` lives as block-quantized int8 or packed
    int4 and is dequantized inside the matmul tiles.

    ``x (..., k)`` activations (fp32/bf16); ``qweight`` int8 — shape
    ``(k, n)`` for ``weight_dtype="int8"``, ``(k, n / 2)`` packed for
    ``"int4"``; ``scales (k, n / block_size)`` fp32.  ``block_size``
    defaults to the value the scale shape implies.  Returns
    ``(..., n)`` in ``x``'s dtype.  ``implementation``: None = auto
    (Pallas on TPU, XLA elsewhere), ``"pallas"``/``"xla"`` force."""
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(
            f"weight_dtype must be 'int8' or 'int4', got "
            f"{weight_dtype!r}")
    if qweight.dtype != jnp.int8:
        raise ValueError(
            f"qweight must be int8 storage, got {qweight.dtype}")
    if qweight.ndim != 2 or scales.ndim != 2:
        raise ValueError(
            f"qweight/scales must be 2-D, got {qweight.shape} / "
            f"{scales.shape}")
    k = x.shape[-1]
    if qweight.shape[0] != k or scales.shape[0] != k:
        raise ValueError(
            f"contraction mismatch: x (..., {k}) vs qweight "
            f"{tuple(qweight.shape)} / scales {tuple(scales.shape)}")
    nb = scales.shape[1]
    n = qweight.shape[1] * (2 if weight_dtype == "int4" else 1)
    if nb < 1 or n % nb:
        raise ValueError(
            f"scales ({nb} blocks) do not tile the {n} output "
            f"features evenly")
    bs = n // nb
    if block_size is not None and int(block_size) != bs:
        raise ValueError(
            f"block_size={block_size} disagrees with the scale shape "
            f"({nb} blocks over {n} features imply {bs})")
    if weight_dtype == "int4" and (nb % 2 or (n // 2) % bs):
        raise ValueError(
            f"int4 halves layout needs whole scale blocks per half: "
            f"n={n} features, block_size={bs} "
            f"({nb} blocks — need an even count per half)")

    lead = x.shape[:-1]
    x2 = x.reshape(-1, k)
    impl = implementation or default_implementation()

    def _pallas():
        if weight_dtype == "int8":
            return _int8_pallas(x2, qweight, scales, bs)
        return _int4_pallas(x2, qweight, scales, bs)

    def _xla():
        return dequant_matmul_reference(
            x2, qweight, scales, weight_dtype=weight_dtype,
            block_size=bs)

    out = run_kernel("dequant_matmul", _pallas, _xla, implementation,
                     impl)
    return out.reshape(*lead, n)


# ----------------------------------------------- weight-pool builders
def quantize_weight(w: jnp.ndarray, weight_dtype: str,
                    block_size: int = 128, *,
                    leaf: str = "weight") -> Dict[str, jnp.ndarray]:
    """ONE ``(k, n)`` weight matrix → its quantized-pool leaf: ``{"q8":
    values, "scales": ...}`` for int8, ``{"q4": packed, "scales": ...}``
    for int4.  The dict KEY is the static type marker — the serving
    forward dispatches on pytree structure, so quantized and
    full-width params trace to different (correct) programs with no
    dynamic flag threading.  ``leaf`` names the weight in the strict
    block-validation errors."""
    if weight_dtype == "int8":
        q, s = quantize_rows(w, block_size, leaf=leaf)
        return {"q8": q, "scales": s}
    if weight_dtype == "int4":
        q, s = quantize_rows_int4(w, block_size, leaf=leaf)
        return {"q4": q, "scales": s}
    raise ValueError(
        f"weight_dtype must be 'int8' or 'int4', got {weight_dtype!r}")


def weight_pool_dtype(wq: Dict[str, Any]) -> str:
    """``"int8"`` / ``"int4"`` from a quantized-pool leaf's marker key."""
    if "q8" in wq:
        return "int8"
    if "q4" in wq:
        return "int4"
    raise ValueError(
        f"not a quantized weight leaf (no 'q8'/'q4' key): "
        f"{sorted(wq)}")


def weight_pool_block(wq: Dict[str, Any]) -> int:
    """The block size a quantized-pool leaf was built with, recovered
    from its shapes (the static info rides in the pytree, never as a
    side-channel flag)."""
    wd = weight_pool_dtype(wq)
    q = wq["q8"] if wd == "int8" else wq["q4"]
    n = q.shape[-1] * (2 if wd == "int4" else 1)
    return n // wq["scales"].shape[-1]


def dequantize_weight(wq: Dict[str, Any],
                      dtype: Any = jnp.float32) -> jnp.ndarray:
    """Materialize a quantized-pool leaf back to a wide matrix — the
    reference/debug path only; the serving forward never calls this."""
    wd = weight_pool_dtype(wq)
    bs = weight_pool_block(wq)
    q = wq["q8"] if wd == "int8" else unpack_int4(wq["q4"])
    return dequantize_rows(q, wq["scales"], bs, dtype)

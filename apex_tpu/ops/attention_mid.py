"""Mid-sequence pipelined attention (fmha-mid): streamed K/V + bh packing.

The middle tier of the attention dispatch ladder
(``docs/attention.md``), covering 512 < s <= ~2048 — the band the
flagship actually trains in.  PROFILE_r05.md measured the flash kernel
at 10.2 TF/s fwd at s=1024 causal vs ~50 TF/s at s>=4096: with the
measured-optimal 1024x1024 blocks the whole K/V sequence sits in ONE
block, so the streamed-K/V design degenerates to one fused attention
per (b, h) with no software pipelining to hide the VPU softmax chain
between the two MXU dots — and causal costs the same wall time as full
(0.843 vs 0.857 ms) because there are no blocks to skip.

This kernel restores the pipeline at mid lengths by doing three things
the flash kernel's shape degeneracy loses:

- **k-blocks smaller than the sequence** (256/512 default): the kb grid
  axis streams K/V through VMEM with Mosaic's revolving-buffer
  (double-buffered) pipelining, and within a program the qk dot of
  block kb+1 has no data dependence on the softmax chain of block kb,
  so the MXU runs under the VPU instead of waiting for it;
- **bh packing above s=512** (PR 1's ``block_bh`` trick lifted past the
  short-kernel window): each program holds ``block_bh`` (batch*head)
  tiles resident and issues their dots back-to-back from one unrolled
  body, keeping the MXU fed when per-(b, h) work is small;
- **causal block-skipping that actually fires**: the per-q-block upper
  bound on the kb loop (same logic the flash kernel carries) now has
  num_k > 1 blocks to skip, so causal does ~half the work of full
  instead of identical work.

The backward is ONE fused kernel emitting dq/dk/dv (and dbias) per the
PR 1 contract — the flash split (dkv + dq kernels) exists to bound
residency across long-sequence block loops, which the mid band does
not need: dq lives whole in a VMEM scratch (``block_bh_bwd`` is sized
so it fits) while dk/dv accumulate per k-block, so q/k/v/do are read
once and the score replay (s, p, dp, dz) happens once.

Feature parity with the flash and short kernels is total: additive
bias (all broadcast batchings) with a real bias gradient, segment-id
varlen masking, and counter-based dropout replayed from the SAME hash
(``attention._keep_mask``) with the SAME (bh, q, k) indexing — so for
a given seed all three kernels and the XLA reference drop bit-identical
entries.

``return_lse=True`` additionally returns the per-row log-sum-exp, with
a real lse cotangent in the fused backward (``dz = p*(dp - delta +
dlse)``) — this is what lets ``ops/ring_attention.py`` run its
per-shard inner attention through this kernel and merge ring blocks by
lse outside it.

Dispatch: ``flash_attention(implementation=None)`` auto-routes here for
short-crossover < s <= ``FMHA_MID_MAX_SEQ`` (env-overridable via
``APEX_TPU_FMHA_MID_MAX_SEQ``, 0 disables — pinning the ladder back to
the flash kernel bit-identically); ``implementation="mid"`` forces this
kernel (strict — lowering failures raise).  The crossover default is
PROVISIONAL until the next TPU capture: ``tools/kernel_validation.py``
sweeps mid-vs-flash-vs-XLA across the band and GATES on this constant
agreeing with the measurement, plus a causal-beats-full gate at s=1024
(the block-skip proof).
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.attention import (
    _LANES,
    _NEG_INF,
    _interpret,
    _keep_mask,
    _keep_threshold,
    _mask_specialized,
    _pad_seq,
    _prec,
    BIAS_PER_BATCH,
    BIAS_PER_HEAD,
    mha_reference,
)
from apex_tpu.ops.common import shape_struct
from apex_tpu.utils.platform import default_implementation

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None

__all__ = [
    "fmha_mid", "FMHA_MID_MAX_SEQ", "mid_seq_threshold",
    "default_mid_blocks", "default_mid_block_bh",
]

#: Auto-dispatch crossover: ``flash_attention`` routes to this kernel
#: when max(sq, sk) is above the short-kernel window and at or below
#: this bound.  2048 brackets the band where the flash kernel's
#: measured-optimal 1024x1024 blocks leave it with <= 2 k-blocks to
#: pipeline (10-20 TF/s, KERNELS_TPU.json) while s>=4096 already
#: streams at ~50 TF/s.  PROVISIONAL until the next TPU window:
#: tools/kernel_validation.py measures mid-vs-flash across the band and
#: the capture gates on this constant agreeing with the measurement
#: (the same record-don't-hand-pick contract as FMHA_SHORT_MAX_SEQ).
FMHA_MID_MAX_SEQ = 2048

#: Per-program score-space budget (elements): block_bh is sized so
#: block_bh * block_q * block_k stays at or under this — the same
#: 512*1024 area bound as FLASH_FP32_MAX_BLOCK_AREA and
#: FMHA_SHORT_BLOCK_ELEMS, keeping the worst-case fp32 temporaries near
#: the flash backward's proven-compiling footprint.
FMHA_MID_BLOCK_ELEMS = 512 * 1024

#: Fused-backward dq residency budget (elements): the single backward
#: kernel holds the WHOLE dq extent for its bh block in fp32 VMEM
#: scratch (that is what makes one fused pass possible), so
#: block_bh_bwd * sq_padded * d_padded is capped here (512K elements =
#: 2 MB fp32) and the backward runs with a (possibly smaller) divisor
#: of the forward's block_bh.
FMHA_MID_BWD_DQ_ELEMS = 512 * 1024

#: Unroll bound, same rationale as the short kernel: the bh block is an
#: unrolled python loop of 2-D MXU dots; 16 copies bounds code size.
FMHA_MID_MAX_BLOCK_BH = 16

#: Default block sizes.  256x256 at lane-multiple-of-256 sequence
#: lengths (s=1024 causal then runs 10/16 blocks = 0.625x the full
#: work), 128x128 otherwise (halves the q/k padding waste at ragged
#: lengths like 576/640 and skips even harder: 36/64 at s=1024).
#: kernel_validation.py sweeps alternatives; these are the shipped
#: pre-capture defaults.
MID_BLOCK_Q = 256
MID_BLOCK_K = 256


def mid_seq_threshold() -> int:
    """The mid-tier auto-dispatch crossover, env-overridable so an ops
    rollout can move the boundary without a code change
    (``APEX_TPU_FMHA_MID_MAX_SEQ=0`` disables mid dispatch, pinning the
    ladder's upper tiers back to the flash kernel)."""
    v = os.environ.get("APEX_TPU_FMHA_MID_MAX_SEQ")
    return int(v) if v is not None and v != "" else FMHA_MID_MAX_SEQ


def default_mid_blocks(sq_p: int, sk_p: int):
    """(block_q, block_k) for padded sequence extents.

    Prefers the 256x256 default; drops to 128 along an axis whose
    lane-rounded extent is not a 256 multiple (ragged mid lengths like
    576/640) so block padding stays at most one 128 tile.
    """
    bq = MID_BLOCK_Q if sq_p % MID_BLOCK_Q == 0 else 128
    bk = MID_BLOCK_K if sk_p % MID_BLOCK_K == 0 else 128
    return min(bq, sq_p), min(bk, sk_p)


def default_mid_block_bh(block_q: int, block_k: int, bh: int) -> int:
    """How many (batch*head) tiles one grid step packs (forward)."""
    by_area = max(1, FMHA_MID_BLOCK_ELEMS // (block_q * block_k))
    return max(1, min(by_area, FMHA_MID_MAX_BLOCK_BH, bh))


def _bwd_block_bh(block_bh: int, sq_p: int, d_p: int) -> int:
    """Largest divisor of the forward ``block_bh`` whose whole-dq
    scratch fits the backward residency budget."""
    cap = max(1, FMHA_MID_BWD_DQ_ELEMS // (sq_p * d_p))
    bb = block_bh
    while bb > 1 and (bb > cap or block_bh % bb):
        bb -= 1
    return max(1, bb)


class _MidConfig(NamedTuple):
    """Static kernel configuration (hashable for custom_vjp)."""

    sm_scale: float
    causal: bool
    dropout_rate: float
    block_q: int
    block_k: int
    block_bh: int       # forward packing
    block_bh_bwd: int    # divisor of block_bh, sized by dq residency
    q_len: int           # unpadded
    kv_len: int          # unpadded
    heads: int           # heads per batch entry (per-batch bias maps)
    # flattened-bias batching, same encoding as the flash kernel:
    # 0 = no bias, 1 = one shared (sq, sk) bias, BIAS_PER_BATCH /
    # BIAS_PER_HEAD as in ops/attention.py
    bias_batch: int
    bias_grad: bool
    hi_precision: bool = False
    # whether the primal returns (out, lse) and the backward consumes a
    # real dlse cotangent (the ring-attention merge path)
    with_lse: bool = False


def _dot2(a, b, contract, cfg):
    return jax.lax.dot_general(
        a, b, (contract, ((), ())),
        preferred_element_type=jnp.float32,
        precision=_prec(cfg),
    )


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _mid_fwd_kernel(
    *refs, cfg: _MidConfig, num_k: int, has_bias, has_segs, has_dropout,
):
    (q_ref, k_ref, v_ref), rest = refs[:3], refs[3:]
    bias_ref = qseg_ref = kseg_ref = seed_ref = None
    if has_bias:
        bias_ref, rest = rest[0], rest[1:]
    if has_segs:
        (qseg_ref, kseg_ref), rest = rest[:2], rest[2:]
    if has_dropout:
        seed_ref, rest = rest[0], rest[1:]
    o_ref, lse_ref, acc_ref, m_ref, l_ref = rest

    i, j, kb = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    block_q, block_k = cfg.block_q, cfg.block_k
    if cfg.causal:
        last_kb = jnp.minimum(num_k - 1, ((j + 1) * block_q - 1) // block_k)
    else:
        last_kb = num_k - 1

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _body(masked):
        if masked or has_dropout:
            q_idx = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
        for bi in range(cfg.block_bh):
            q = q_ref[bi].astype(jnp.float32) * cfg.sm_scale  # (bq, d)
            s = _dot2(q, k_ref[bi].astype(jnp.float32),
                      ((1,), (1,)), cfg)                      # (bq, bk)
            if has_bias:
                s = s + bias_ref[
                    bi if cfg.bias_batch == BIAS_PER_HEAD else 0
                ].astype(jnp.float32)
            if masked:
                mask = k_idx < cfg.kv_len
                if cfg.causal:
                    mask = jnp.logical_and(mask, k_idx <= q_idx)
                if has_segs:
                    mask = jnp.logical_and(
                        mask,
                        qseg_ref[bi, 0][:, None] == kseg_ref[bi, 0][None, :],
                    )
                s = jnp.where(mask, s, _NEG_INF)
            m_prev = m_ref[bi, :, 0:1]
            l_prev = l_ref[bi, :, 0:1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            if masked:
                p = jnp.where(mask, p, 0.0)
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
            if has_dropout:
                keep = _keep_mask(
                    seed_ref[0, 0], i * cfg.block_bh + bi, q_idx, k_idx,
                    jnp.uint32(_keep_threshold(cfg.dropout_rate)),
                )
                p_acc = jnp.where(keep, p, 0.0) * (
                    1.0 / (1.0 - cfg.dropout_rate))
            else:
                p_acc = p
            acc_ref[bi] = acc_ref[bi] * corr + _dot2(
                p_acc, v_ref[bi].astype(jnp.float32), ((1,), (0,)), cfg
            )
            m_ref[bi] = jnp.broadcast_to(m_new, m_ref.shape[1:])
            l_ref[bi] = jnp.broadcast_to(l_new, l_ref.shape[1:])

    conds = []
    if cfg.causal:
        conds.append(kb * block_k + (block_k - 1) > j * block_q)
    if cfg.kv_len < num_k * block_k:                         # kv padding
        conds.append(kb == num_k - 1)
    _mask_specialized(kb <= last_kb, conds, has_segs, _body)

    @pl.when(kb == last_kb)
    def _finalize():
        for bi in range(cfg.block_bh):
            l = jnp.maximum(l_ref[bi, :, 0:1], 1e-30)
            o_ref[bi] = (acc_ref[bi] / l).astype(o_ref.dtype)
            lse_ref[bi, 0] = m_ref[bi, :, 0] + jnp.log(l[:, 0])


# ---------------------------------------------------------------------------
# Fused backward kernel (dq + dk + dv + optional dbias in one pass)
# ---------------------------------------------------------------------------


def _mid_bwd_kernel(
    *refs, cfg: _MidConfig, num_q: int, num_k: int, has_bias, has_segs,
    has_dropout,
):
    bb = cfg.block_bh_bwd
    (q_ref, k_ref, v_ref), rest = refs[:3], refs[3:]
    bias_ref = qseg_ref = kseg_ref = seed_ref = None
    if has_bias:
        bias_ref, rest = rest[0], rest[1:]
    if has_segs:
        (qseg_ref, kseg_ref), rest = rest[:2], rest[2:]
    if has_dropout:
        seed_ref, rest = rest[0], rest[1:]
    do_ref, lse_ref, delta_ref = rest[:3]
    rest = rest[3:]
    dlse_ref = None
    if cfg.with_lse:
        dlse_ref, rest = rest[0], rest[1:]
    emit_dbias = has_bias and cfg.bias_grad
    if emit_dbias:
        dq_ref, dk_ref, dv_ref, dbias_ref = rest[:4]
        rest = rest[4:]
    else:
        (dq_ref, dk_ref, dv_ref), rest = rest[:3], rest[3:]
        dbias_ref = None
    dq_acc, dk_acc, dv_acc = rest

    i, kb, jq = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    block_q, block_k = cfg.block_q, cfg.block_k
    # under causal masking, q blocks strictly above the diagonal band
    # contribute nothing to this k block — but with a bias gradient
    # every (jq, kb) dbias block must still be written, so the skip only
    # applies when dbias is not emitted (flash-kernel contract)
    first_jq = (kb * block_k) // block_q if (
        cfg.causal and not emit_dbias) else 0

    @pl.when(jnp.logical_and(kb == 0, jq == 0))
    def _init_dq():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(jq == 0)
    def _init_dkv():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _body(masked):
        if masked or has_dropout:
            q_idx = jq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
        for bi in range(bb):
            qblk = q_ref[bi].astype(jnp.float32)             # (bq, d)
            kblk = k_ref[bi].astype(jnp.float32)             # (bk, d)
            vblk = v_ref[bi].astype(jnp.float32)
            doblk = do_ref[bi].astype(jnp.float32)
            lse = lse_ref[bi, 0][:, None]                    # (bq, 1)
            delta = delta_ref[bi, 0][:, None]
            s = _dot2(qblk, kblk, ((1,), (1,)), cfg) * cfg.sm_scale
            if has_bias:
                s = s + bias_ref[
                    bi if cfg.bias_batch == BIAS_PER_HEAD else 0
                ].astype(jnp.float32)
            p = jnp.exp(s - lse)
            if masked:
                mask = jnp.logical_and(
                    q_idx < cfg.q_len, k_idx < cfg.kv_len
                )
                if cfg.causal:
                    mask = jnp.logical_and(mask, k_idx <= q_idx)
                if has_segs:
                    mask = jnp.logical_and(
                        mask,
                        qseg_ref[bi, 0][:, None] == kseg_ref[bi, 0][None, :],
                    )
                p = jnp.where(mask, p, 0.0)
            dp = _dot2(doblk, vblk, ((1,), (1,)), cfg)       # (bq, bk)
            if has_dropout:
                keep = _keep_mask(
                    seed_ref[0, 0], i * bb + bi, q_idx, k_idx,
                    jnp.uint32(_keep_threshold(cfg.dropout_rate)),
                )
                inv_kp = 1.0 / (1.0 - cfg.dropout_rate)
                p_drop = jnp.where(keep, p, 0.0) * inv_kp
                dp = jnp.where(keep, dp, 0.0) * inv_kp
            else:
                p_drop = p
            dv_acc[bi] += _dot2(p_drop, doblk, ((0,), (0,)), cfg)
            resid = dp - delta                               # grad wrt s
            if cfg.with_lse:
                # lse cotangent: d lse_i / d s_ij = p_ij (the normalized
                # softmax), independent of dropout — one extra row add
                resid = resid + dlse_ref[bi, 0][:, None]
            dz = p * resid                                   # grad wrt s+bias
            if emit_dbias:
                if cfg.bias_batch == BIAS_PER_HEAD:
                    dbias_ref[bi] = dz.astype(dbias_ref.dtype)
                elif bi == 0:
                    dbias_ref[0] = dz.astype(dbias_ref.dtype)
                else:
                    dbias_ref[0] += dz.astype(dbias_ref.dtype)
            dk_acc[bi] += _dot2(dz * cfg.sm_scale, qblk, ((0,), (0,)), cfg)
            dq_acc[bi, pl.ds(jq * block_q, block_q), :] += _dot2(
                dz * cfg.sm_scale, kblk, ((1,), (0,)), cfg
            )

    # a (jq, kb) block needs masking iff it intersects the causal
    # diagonal, is the padded q tail (garbage lse/delta rows would
    # pollute dk/dv), or the padded kv tail (garbage k cols would
    # pollute dq)
    conds = []
    if cfg.causal:
        conds.append(kb * block_k + (block_k - 1) > jq * block_q)
    if cfg.q_len < num_q * block_q:                          # q padding
        conds.append(jq == num_q - 1)
    if cfg.kv_len < num_k * block_k:                         # kv padding
        conds.append(kb == num_k - 1)
    if emit_dbias:
        # every block runs so every dbias block is written; the mask
        # keeps skippable blocks' contributions at exactly zero
        run = jq <= num_q - 1
    else:
        run = jq >= first_jq
    _mask_specialized(run, conds, has_segs, _body)

    @pl.when(jq == num_q - 1)
    def _write_dkv():
        for bi in range(bb):
            dk_ref[bi] = dk_acc[bi].astype(dk_ref.dtype)
            dv_ref[bi] = dv_acc[bi].astype(dv_ref.dtype)

    @pl.when(jnp.logical_and(kb == num_k - 1, jq == num_q - 1))
    def _write_dq():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


def _bias_spec(cfg, bb, block_q, block_k, wire):
    """Bias BlockSpec for a grid whose (q-block, k-block) coordinates are
    produced by ``wire`` (identity for the fwd (i, j, kb) grid, a swap
    for the bwd (i, kb, jq) grid)."""
    heads = cfg.heads
    if cfg.bias_batch == BIAS_PER_HEAD:
        return pl.BlockSpec((bb, block_q, block_k),
                            wire(lambda i, j, kb: (i, j, kb)),
                            memory_space=pltpu.VMEM)
    if cfg.bias_batch == BIAS_PER_BATCH:
        # block_bh divides heads (wrapper invariant), so program i
        # covers bh rows of exactly one batch entry
        return pl.BlockSpec(
            (1, block_q, block_k),
            wire(lambda i, j, kb: ((i * bb) // heads, j, kb)),
            memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, block_q, block_k),
                        wire(lambda i, j, kb: (0, j, kb)),
                        memory_space=pltpu.VMEM)


def _in_specs(cfg, bb, d_p, has_bias, has_segs, has_dropout,
              swap_grid=False):
    """Input BlockSpecs for q/k/v (+bias/segs/seed).  Index maps are
    written for the forward (i, jq, kb) grid; ``swap_grid`` rewires them
    for the backward's (i, kb, jq) grid."""
    block_q, block_k = cfg.block_q, cfg.block_k

    def w(f):
        if not swap_grid:
            return f
        return lambda i, kb, jq: f(i, jq, kb)

    specs = [
        pl.BlockSpec((bb, block_q, d_p), w(lambda i, j, kb: (i, j, 0)),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bb, block_k, d_p), w(lambda i, j, kb: (i, kb, 0)),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bb, block_k, d_p), w(lambda i, j, kb: (i, kb, 0)),
                     memory_space=pltpu.VMEM),
    ]
    if has_bias:
        specs.append(_bias_spec(cfg, bb, block_q, block_k, w))
    if has_segs:
        # (bh, 1, s) layout: the middle singleton keeps the trailing
        # two block dims Mosaic-tileable, same trick as flash/short
        specs.append(pl.BlockSpec((bb, 1, block_q),
                                  w(lambda i, j, kb: (i, 0, j))))
        specs.append(pl.BlockSpec((bb, 1, block_k),
                                  w(lambda i, j, kb: (i, 0, kb))))
    if has_dropout:
        specs.append(pl.BlockSpec((1, 1), w(lambda i, j, kb: (0, 0)),
                                  memory_space=pltpu.SMEM))
    return specs


def _compiler_params():
    from apex_tpu.ops.common import tpu_compiler_params

    return tpu_compiler_params(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _bwd_compiler_params():
    from apex_tpu.ops.common import tpu_compiler_params

    # both block axes are serialized: dq accumulates across kb AND jq
    return tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary", "arbitrary")
    )


def _mid_fwd_pallas(q, k, v, bias, qseg, kseg, seed, cfg: _MidConfig):
    bh_p, psq, d_p = q.shape
    psk = k.shape[1]
    num_q, num_k = psq // cfg.block_q, psk // cfg.block_k
    assert psk - cfg.kv_len < cfg.block_k and psq - cfg.q_len < cfg.block_q
    has_bias = bias is not None
    has_segs = qseg is not None
    has_dropout = cfg.dropout_rate > 0.0
    bb = cfg.block_bh
    inputs = [q, k, v]
    if has_bias:
        inputs.append(bias)
    if has_segs:
        inputs.extend([qseg, kseg])
    if has_dropout:
        inputs.append(seed)
    out, lse = pl.pallas_call(
        functools.partial(
            _mid_fwd_kernel, cfg=cfg, num_k=num_k, has_bias=has_bias,
            has_segs=has_segs, has_dropout=has_dropout,
        ),
        grid=(bh_p // bb, num_q, num_k),
        in_specs=_in_specs(cfg, bb, d_p, has_bias, has_segs, has_dropout),
        out_specs=[
            pl.BlockSpec((bb, cfg.block_q, d_p),
                         lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 1, cfg.block_q), lambda i, j, kb: (i, 0, j)),
        ],
        out_shape=[
            shape_struct((bh_p, psq, d_p), q.dtype, q, k, v),
            shape_struct((bh_p, 1, psq), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((bb, cfg.block_q, d_p), jnp.float32),
            pltpu.VMEM((bb, cfg.block_q, _LANES), jnp.float32),
            pltpu.VMEM((bb, cfg.block_q, _LANES), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*inputs)
    return out, lse


def _mid_bwd_pallas(q, k, v, bias, qseg, kseg, seed, out, lse, do, dlse,
                    cfg: _MidConfig):
    bh_p, psq, d_p = q.shape
    psk = k.shape[1]
    num_q, num_k = psq // cfg.block_q, psk // cfg.block_k
    assert psk - cfg.kv_len < cfg.block_k and psq - cfg.q_len < cfg.block_q
    has_bias = bias is not None
    has_segs = qseg is not None
    has_dropout = cfg.dropout_rate > 0.0
    emit_dbias = has_bias and cfg.bias_grad
    bb = cfg.block_bh_bwd
    # delta = rowsum(do * o) — cheap, XLA fuses it
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )[:, None, :]

    inputs = [q, k, v]
    if has_bias:
        inputs.append(bias)
    if has_segs:
        inputs.extend([qseg, kseg])
    if has_dropout:
        inputs.append(seed)
    inputs.extend([do, lse, delta])
    if cfg.with_lse:
        inputs.append(dlse.astype(jnp.float32)[:, None, :])

    in_specs = _in_specs(cfg, bb, d_p, has_bias, has_segs, has_dropout,
                         swap_grid=True)
    in_specs.extend([
        pl.BlockSpec((bb, cfg.block_q, d_p), lambda i, kb, jq: (i, jq, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bb, 1, cfg.block_q), lambda i, kb, jq: (i, 0, jq)),
        pl.BlockSpec((bb, 1, cfg.block_q), lambda i, kb, jq: (i, 0, jq)),
    ])
    if cfg.with_lse:
        in_specs.append(
            pl.BlockSpec((bb, 1, cfg.block_q), lambda i, kb, jq: (i, 0, jq))
        )

    out_specs = [
        # dq flushes ONCE per bh block (constant index map over the two
        # serialized axes) from the whole-extent scratch
        pl.BlockSpec((bb, psq, d_p), lambda i, kb, jq: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bb, cfg.block_k, d_p), lambda i, kb, jq: (i, kb, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((bb, cfg.block_k, d_p), lambda i, kb, jq: (i, kb, 0),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        shape_struct((bh_p, psq, d_p), q.dtype, q, k, v, do),
        shape_struct((bh_p, psk, d_p), k.dtype, q, k, v, do),
        shape_struct((bh_p, psk, d_p), v.dtype, q, k, v, do),
    ]
    if emit_dbias:
        if cfg.bias_batch == BIAS_PER_HEAD:
            out_specs.append(pl.BlockSpec(
                (bb, cfg.block_q, cfg.block_k),
                lambda i, kb, jq: (i, jq, kb), memory_space=pltpu.VMEM))
            out_shape.append(
                shape_struct((bh_p, psq, psk), jnp.float32, q, k, v, do))
        else:
            # shared/per_batch: per-PROGRAM partial sums over the bh
            # block; the vjp folds the program axis back in XLA
            n_prog = bh_p // bb
            out_specs.append(pl.BlockSpec(
                (1, cfg.block_q, cfg.block_k),
                lambda i, kb, jq: (i, jq, kb), memory_space=pltpu.VMEM))
            out_shape.append(
                shape_struct((n_prog, psq, psk), jnp.float32, q, k, v, do))
    res = pl.pallas_call(
        functools.partial(
            _mid_bwd_kernel, cfg=cfg, num_q=num_q, num_k=num_k,
            has_bias=has_bias, has_segs=has_segs, has_dropout=has_dropout,
        ),
        grid=(bh_p // bb, num_k, num_q),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bb, psq, d_p), jnp.float32),
            pltpu.VMEM((bb, cfg.block_k, d_p), jnp.float32),
            pltpu.VMEM((bb, cfg.block_k, d_p), jnp.float32),
        ],
        compiler_params=_bwd_compiler_params(),
        interpret=_interpret(),
    )(*inputs)
    if emit_dbias:
        dq, dk, dv, dbias = res
    else:
        (dq, dk, dv), dbias = res, None
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# custom_vjp wrapper (flattened, padded (bh_p, s_p, d_p) layout)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def _mid(q, k, v, bias, qseg, kseg, seed, cfg):
    out, lse = _mid_fwd_pallas(q, k, v, bias, qseg, kseg, seed, cfg)
    if cfg.with_lse:
        return out, lse[:, 0]
    return out


def _mid_fwd(q, k, v, bias, qseg, kseg, seed, cfg):
    out, lse = _mid_fwd_pallas(q, k, v, bias, qseg, kseg, seed, cfg)
    res = (q, k, v, bias, qseg, kseg, seed, out, lse)
    if cfg.with_lse:
        return (out, lse[:, 0]), res
    return out, res


def _int_zero(x):
    return (
        None if x is None
        else np.zeros(x.shape, jax.dtypes.float0)
    )


def _mid_bwd(cfg, res, ct):
    q, k, v, bias, qseg, kseg, seed, out, lse = res
    if cfg.with_lse:
        do, dlse = ct
    else:
        do, dlse = ct, None
    dq, dk, dv, dbias = _mid_bwd_pallas(
        q, k, v, bias, qseg, kseg, seed, out, lse, do, dlse, cfg
    )
    if bias is not None and not cfg.bias_grad:
        # constant-mask contract: caller declared the bias non-trainable
        dbias = jnp.zeros_like(bias)
    elif bias is not None:
        if cfg.bias_batch == 1:
            # fold the per-program partial sums back to the one shared
            # (1, sq, sk) bias block the primal consumed
            dbias = jnp.sum(dbias, axis=0, keepdims=True)
        elif cfg.bias_batch == BIAS_PER_BATCH:
            # (n_prog, sq, sk) partial sums, heads//block_bh_bwd
            # programs per batch entry → (b, sq, sk), the primal's shape
            n_prog, psq, psk = dbias.shape
            per_batch = cfg.heads // cfg.block_bh_bwd
            dbias = dbias.reshape(
                n_prog // per_batch, per_batch, psq, psk).sum(axis=1)
        dbias = dbias.astype(bias.dtype)
    return (dq, dk, dv, dbias, _int_zero(qseg), _int_zero(kseg),
            _int_zero(seed))


_mid.defvjp(_mid_fwd, _mid_bwd)


# ---------------------------------------------------------------------------
# XLA fallback with lse (the reference path for return_lse callers)
# ---------------------------------------------------------------------------


def _xla_with_lse(q, k, v, causal, sm_scale, bias, q_segment_ids,
                  kv_segment_ids, dropout_rate, dropout_seed):
    """``mha_reference`` plus the per-row log-sum-exp.

    The output comes from ``mha_reference`` itself (ONE reference
    implementation of the masking/dropout/normalization semantics —
    the cross-kernel dropout-mask and ring-merge parity contracts both
    lean on it staying singular); only the lse is computed here, from
    the same masked-score formula every kernel uses.
    """
    out = mha_reference(
        q, k, v, causal=causal, sm_scale=sm_scale, bias=bias,
        q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed,
    )
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = (1.0 / d**0.5) if sm_scale is None else sm_scale
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    mask = jnp.ones((1, 1, sq, sk), bool)
    if causal:
        q_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_idx = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        mask = mask & (k_idx <= q_idx)[None, None]
    if q_segment_ids is not None:
        mask = mask & (
            q_segment_ids[:, None, :, None]
            == kv_segment_ids[:, None, None, :]
        )
    s = jnp.where(mask, s, _NEG_INF)
    m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(jnp.broadcast_to(mask, s.shape), jnp.exp(s - m), 0.0)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    lse = m[..., 0] + jnp.log(l[..., 0])
    return out, lse


# ---------------------------------------------------------------------------
# Public entry point
# ---------------------------------------------------------------------------


def fmha_mid(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
    q_segment_ids: Optional[jnp.ndarray] = None,
    kv_segment_ids: Optional[jnp.ndarray] = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
    bias_requires_grad: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_bh: Optional[int] = None,
    implementation: Optional[str] = None,
    return_lse: bool = False,
):
    """Pipelined mid-sequence attention over ``(b, h, s, d)``.

    Same contract as :func:`~apex_tpu.ops.attention.flash_attention`
    (bias / segment ids / counter-hash dropout, identical masks for a
    given seed), specialized for the band where K/V still fits a few
    streamed blocks: k-block streaming + (batch*head) packing + causal
    block-skipping, with ONE fused backward.  ``block_q``/``block_k``/
    ``block_bh`` override the measured defaults.

    ``return_lse=True`` returns ``(out, lse)`` with ``lse`` of shape
    ``(b, h, sq)`` — differentiable (the fused backward consumes a real
    lse cotangent), which is what the ring-attention merge needs.

    Most callers should not call this directly: ``flash_attention``
    auto-routes here inside the measured window, and accepts
    ``implementation="mid"`` to force this kernel.
    """
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("segment ids must be given for both q and kv")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if bias is not None and bias.ndim < 4:
        bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
    from apex_tpu.ops.common import KernelLoweringError, run_kernel

    if implementation == "mid":
        # the flash_attention-facing spelling: forcing "mid" on the mid
        # entry point itself means the strict kernel path
        implementation = "pallas"
    if implementation not in (None, "pallas", "xla"):
        raise ValueError(
            f"unknown implementation {implementation!r}; expected None, "
            "'pallas'/'mid', or 'xla'"
        )
    if pl is None and implementation == "pallas":
        raise KernelLoweringError(
            "implementation='pallas' requested but Pallas failed to import"
        )
    impl = implementation or default_implementation()
    if pl is None:
        impl = "xla"

    def _xla_path():
        if return_lse:
            return _xla_with_lse(
                q, k, v, causal, sm_scale, bias, q_segment_ids,
                kv_segment_ids, dropout_rate, dropout_seed,
            )
        return mha_reference(
            q, k, v, causal=causal, sm_scale=sm_scale, bias=bias,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )

    def _pallas_path():
        return _fmha_mid_pallas(
            q, k, v, causal, sm_scale, bias, q_segment_ids,
            kv_segment_ids, dropout_rate, dropout_seed,
            bias_requires_grad, block_q, block_k, block_bh, return_lse,
        )

    return run_kernel(
        "fmha_mid", _pallas_path, _xla_path, implementation, impl
    )


def _fmha_mid_pallas(
    q, k, v, causal, sm_scale, bias, q_segment_ids, kv_segment_ids,
    dropout_rate, dropout_seed, bias_requires_grad, block_q, block_k,
    block_bh, return_lse,
):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = (1.0 / d**0.5) if sm_scale is None else float(sm_scale)
    # lane-round the sequence extents first (they are lse lane dims and
    # score sublane/lane dims), then round up to the block sizes
    sq_l = sq + (-sq) % _LANES
    sk_l = sk + (-sk) % _LANES
    if block_q is None or block_k is None:
        dbq, dbk = default_mid_blocks(sq_l, sk_l)
        block_q = dbq if block_q is None else min(int(block_q), sq_l)
        block_k = dbk if block_k is None else min(int(block_k), sk_l)
    else:
        block_q = min(int(block_q), sq_l)
        block_k = min(int(block_k), sk_l)
    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    pad_d = (-d) % _LANES
    d_p = d + pad_d
    if pad_d:
        padd = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        q, k, v = padd(q), padd(k), padd(v)

    bh = b * h
    if block_bh is None:
        bb = default_mid_block_bh(block_q, block_k, bh)
    else:
        bb = max(1, min(int(block_bh), bh))
    bias_batch = 0
    if bias is not None:
        if bias.shape[0] > 1 and bias.shape[1] == 1:
            # per-batch bias rides its native (b, sq, sk) layout; each
            # program must then stay inside one batch entry, so clamp
            # block_bh to a divisor of heads
            bias_batch = BIAS_PER_BATCH
            while h % bb:
                bb -= 1
        elif bias.shape[0] == 1 and bias.shape[1] == 1:
            bias_batch = 1
        else:
            bias_batch = BIAS_PER_HEAD
    pad_bh = (-bh) % bb
    bh_p = bh + pad_bh

    def flat(x, pad_s):
        x = _pad_seq(x.reshape(bh, x.shape[2], x.shape[3]), pad_s)
        return jnp.pad(x, ((0, pad_bh), (0, 0), (0, 0))) if pad_bh else x

    qf, kf, vf = flat(q, pad_q), flat(k, pad_k), flat(v, pad_k)

    bias_flat = None
    if bias is not None:
        if bias_batch == BIAS_PER_BATCH:
            bias_flat = jnp.broadcast_to(
                bias, (b, 1, sq, sk)).reshape(b, sq, sk)
        elif bias_batch == 1:
            bias_flat = jnp.broadcast_to(
                bias, (1, 1, sq, sk)).reshape(1, sq, sk)
        else:
            bias_flat = jnp.broadcast_to(
                bias, (b, h, sq, sk)).reshape(bh, sq, sk)
        bias_flat = _pad_seq(_pad_seq(bias_flat, pad_q, axis=1),
                             pad_k, axis=2)
        if bias_batch == BIAS_PER_HEAD and pad_bh:
            bias_flat = jnp.pad(bias_flat, ((0, pad_bh), (0, 0), (0, 0)))

    qseg = kseg = None
    if q_segment_ids is not None:
        # per-bh segment rows (short-kernel layout): padded q rows keep
        # id 0 (their lse stays finite), padded kv ids get -1 so they
        # never match a real segment
        def seg_flat(ids, pad_s, pad_value):
            ids = jnp.broadcast_to(
                ids.astype(jnp.int32)[:, None, None, :],
                (b, h, 1, ids.shape[1]),
            ).reshape(bh, 1, ids.shape[1])
            if pad_s:
                ids = jnp.pad(ids, ((0, 0), (0, 0), (0, pad_s)),
                              constant_values=pad_value)
            if pad_bh:
                ids = jnp.pad(ids, ((0, pad_bh), (0, 0), (0, 0)),
                              constant_values=pad_value)
            return ids

        qseg = seg_flat(q_segment_ids, pad_q, 0)
        kseg = seg_flat(kv_segment_ids, pad_k, -1)

    seed_arr = None
    if dropout_rate > 0.0:
        seed_arr = jnp.asarray(dropout_seed, jnp.uint32).reshape(1, 1)

    cfg = _MidConfig(
        sm_scale=scale, causal=causal, dropout_rate=float(dropout_rate),
        block_q=block_q, block_k=block_k, block_bh=bb,
        block_bh_bwd=_bwd_block_bh(bb, sq + pad_q, d_p),
        q_len=sq, kv_len=sk, heads=h, bias_batch=bias_batch,
        bias_grad=bool(bias_requires_grad),
        hi_precision=(q.dtype == jnp.float32),
        with_lse=bool(return_lse),
    )
    res = _mid(qf, kf, vf, bias_flat, qseg, kseg, seed_arr, cfg)
    if return_lse:
        out, lse = res
    else:
        out, lse = res, None
    out = out[:bh, :sq].reshape(b, h, sq, d_p)
    if pad_d:
        out = out[..., :d]
    if return_lse:
        lse = lse[:bh, :sq].reshape(b, h, sq)
        return out, lse
    return out

"""apex_tpu.ops — the kernel layer.

TPU-native replacement for the reference's ``csrc/`` CUDA kernel tier
(SURVEY.md §2.2): every op is a jittable function with a Pallas TPU fast
path and a pure-XLA fallback sharing one ``custom_vjp``, so numerics are
identical across backends (the reference's L1 "ext vs python path"
bitwise test philosophy, reference: tests/L1/common/run_test.sh:118-137).
"""

from apex_tpu.ops.layer_norm import (  # noqa: F401
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
    mixed_dtype_fused_layer_norm_affine,
)

__all__ = [
    "fused_layer_norm",
    "fused_layer_norm_affine",
    "fused_rms_norm",
    "fused_rms_norm_affine",
    "mixed_dtype_fused_layer_norm_affine",
]

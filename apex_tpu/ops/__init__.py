"""apex_tpu.ops — the kernel layer.

TPU-native replacement for the reference's ``csrc/`` CUDA kernel tier
(SURVEY.md §2.2): every op is a jittable function with a Pallas TPU fast
path and a pure-XLA fallback sharing one ``custom_vjp``, so numerics are
identical across backends (the reference's L1 "ext vs python path"
bitwise test philosophy, reference: tests/L1/common/run_test.sh:118-137).
"""

from apex_tpu.ops.layer_norm import (  # noqa: F401
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
    mixed_dtype_fused_layer_norm_affine,
)
from apex_tpu.ops.softmax import (  # noqa: F401
    scaled_softmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.ops.attention import (  # noqa: F401
    flash_attention,
    mha_reference,
)
from apex_tpu.ops.attention_short import (  # noqa: F401
    fmha_short,
)
from apex_tpu.ops.attention_mid import (  # noqa: F401
    fmha_mid,
)
from apex_tpu.ops.quantization import (  # noqa: F401
    CompressionConfig,
    dequantize_blockwise,
    quantize_blockwise,
    quantized_psum,
)
from apex_tpu.ops.dequant_matmul import (  # noqa: F401
    dequant_matmul,
    quantize_weight,
)

__all__ = [
    "CompressionConfig",
    "dequantize_blockwise",
    "quantize_blockwise",
    "quantized_psum",
    "dequant_matmul",
    "quantize_weight",
    "fmha_mid",
    "fmha_short",
    "fused_layer_norm",
    "fused_layer_norm_affine",
    "fused_rms_norm",
    "fused_rms_norm_affine",
    "mixed_dtype_fused_layer_norm_affine",
    "scaled_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "flash_attention",
    "mha_reference",
]

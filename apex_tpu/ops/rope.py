"""Rotary position embeddings (RoPE), fused by XLA.

Closes the reference fork's mentioned-but-absent rope capability
(reference: SURVEY.md §2.1 "transformer.layers (fused RoPE note)" — the
fork's BASELINE mentions rope, but csrc/megatron ships only softmax
kernels).  TPU design note: RoPE is a pure elementwise rotation of the
(q, k) projections, so the right "fused kernel" on TPU is none at all —
XLA fuses the rotate into the projection epilogue / attention prologue,
and a hand-written Pallas kernel could only add launch overhead (same
decision record as layer norm / softmax, docs/kernels.md).

Convention: half-split rotate (Llama/NeoX style) — the head dim is
split into two halves forming (x1, x2) pairs rotated by
position-dependent angles; frequencies follow the original RoPE
geometric ladder ``base**(-2i/d)``.  Trig runs in fp32 regardless of
the activation dtype (bf16 angles visibly drift past ~2k positions),
and the rotation is applied in fp32 then cast back.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "rope_cos_sin", "apply_rope", "apply_rope_tables", "rope_table",
    "apply_rope_at",
]


def rope_cos_sin(
    positions: jnp.ndarray, head_dim: int, base: float = 10000.0
):
    """(cos, sin) tables for ``positions`` (any shape, int), each of
    shape ``positions.shape + (head_dim // 2,)``, fp32."""
    if head_dim % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {head_dim}")
    half = head_dim // 2
    inv_freq = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray,
    positions: Optional[jnp.ndarray] = None,
    *,
    base: float = 10000.0,
    position_offset: int = 0,
) -> jnp.ndarray:
    """Rotate ``x`` of shape (..., seq, head_dim) by its positions.

    ``positions`` defaults to ``offset + arange(seq)`` —
    ``position_offset`` is the context-parallel hook: cp rank r passes
    ``r * local_seq`` so its sequence chunk is rotated by GLOBAL
    positions (the same contract as the learned table's ``_pos_slice``,
    models/gpt.py).  Output dtype matches the input.
    """
    seq, d = x.shape[-2], x.shape[-1]
    if positions is None:
        positions = position_offset + jnp.arange(seq, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions, d, base)  # (seq, d/2) fp32
    return apply_rope_tables(x, cos, sin)


def apply_rope_tables(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotate by PRECOMPUTED (cos, sin) tables of shape (seq, d/2).

    Separate entry so callers scanning over layers (models/gpt.py) can
    compute the trig once and close over the tables — a scan body can't
    hoist the iota+trig itself, so the fused form would re-run it every
    layer and again in the remat backward."""
    d = x.shape[-1]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# Incremental decode: position-indexed application + cached tables
# ---------------------------------------------------------------------------

#: (max_len, head_dim, dtype_name, base) -> (cos, sin) tables.  Decode
#: calls rotate ONE position per sequence per step; recomputing the
#: trig ladder every step would put an iota+cos+sin chain in front of
#: every cache write, so the full table is built once per
#: (max_len, dim, dtype) and the per-step work is a row gather.
_TABLE_CACHE: dict = {}


def rope_table(
    max_len: int, head_dim: int, dtype: Any = jnp.float32,
    base: float = 10000.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Cached ``(cos, sin)`` tables of shape ``(max_len, head_dim//2)``,
    keyed by ``(max_len, head_dim, dtype, base)``.  Rows are computed by
    the same formula :func:`rope_cos_sin` evaluates, so gathering row
    ``p`` is BIT-identical to computing position ``p`` directly (the
    incremental-vs-full-sequence identity tests/test_rope.py pins).

    ``dtype`` below fp32 trades table bytes for the documented >2k-
    position drift (module docstring) — fp32 is the default for a
    reason."""
    key = (int(max_len), int(head_dim), jnp.dtype(dtype).name,
           float(base))
    hit = _TABLE_CACHE.get(key)
    if hit is None:
        # eager even under an active jit trace (GPTModel.decode_step
        # calls this while being traced): without the escape the cached
        # values would be TRACERS, poisoning every later trace that
        # reads the cache (UnexpectedTracerError)
        with jax.ensure_compile_time_eval():
            cos, sin = rope_cos_sin(
                jnp.arange(max_len, dtype=jnp.int32), head_dim, base
            )
            hit = (cos.astype(dtype), sin.astype(dtype))
        _TABLE_CACHE[key] = hit
    return hit


def apply_rope_at(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    base: float = 10000.0,
    max_len: Optional[int] = None,
    tables: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
) -> jnp.ndarray:
    """Rotate ``x`` at ARBITRARY per-sequence positions — the
    incremental-decode entry: each serving slot sits at its own offset
    and advances one position per step, so the full-sequence
    ``apply_rope`` (whole-table recompute, shared positions) does not
    fit.

    ``positions`` is ``(s,)`` (shared across the batch, any ``x``
    layout ``(..., s, d)``) or ``(b, s)`` (per-sequence, ``x`` then
    ``(b, h, s, d)``).  Tables come from ``tables=`` or the
    :func:`rope_table` cache when ``max_len`` is given; with neither,
    the trig is computed directly for just these positions
    (:func:`rope_cos_sin`) — all three sources are bit-identical."""
    d = x.shape[-1]
    positions = jnp.asarray(positions)
    if tables is None and max_len is not None:
        tables = rope_table(max_len, d, base=base)
    if tables is not None:
        cos = jnp.take(tables[0], positions, axis=0).astype(jnp.float32)
        sin = jnp.take(tables[1], positions, axis=0).astype(jnp.float32)
    else:
        cos, sin = rope_cos_sin(positions, d, base)
    if positions.ndim == 2:
        if x.ndim != 4:
            raise ValueError(
                f"per-sequence (b, s) positions need x of shape "
                f"(b, h, s, d), got {x.shape}"
            )
        cos, sin = cos[:, None], sin[:, None]   # broadcast over heads
    return apply_rope_tables(x, cos, sin)

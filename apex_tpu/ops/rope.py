"""Rotary position embeddings (RoPE), fused by XLA.

Closes the reference fork's mentioned-but-absent rope capability
(reference: SURVEY.md §2.1 "transformer.layers (fused RoPE note)" — the
fork's BASELINE mentions rope, but csrc/megatron ships only softmax
kernels).  TPU design note: RoPE is a pure elementwise rotation of the
(q, k) projections, so the right "fused kernel" on TPU is none at all —
XLA fuses the rotate into the projection epilogue / attention prologue,
and a hand-written Pallas kernel could only add launch overhead (same
decision record as layer norm / softmax, docs/kernels.md).

Convention: half-split rotate (Llama/NeoX style) — the head dim is
split into two halves forming (x1, x2) pairs rotated by
position-dependent angles; frequencies follow the original RoPE
geometric ladder ``base**(-2i/d)``.  Trig runs in fp32 regardless of
the activation dtype (bf16 angles visibly drift past ~2k positions),
and the rotation is applied in fp32 then cast back.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

__all__ = ["rope_cos_sin", "apply_rope", "apply_rope_tables"]


def rope_cos_sin(
    positions: jnp.ndarray, head_dim: int, base: float = 10000.0
):
    """(cos, sin) tables for ``positions`` (any shape, int), each of
    shape ``positions.shape + (head_dim // 2,)``, fp32."""
    if head_dim % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {head_dim}")
    half = head_dim // 2
    inv_freq = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray,
    positions: Optional[jnp.ndarray] = None,
    *,
    base: float = 10000.0,
    position_offset: int = 0,
) -> jnp.ndarray:
    """Rotate ``x`` of shape (..., seq, head_dim) by its positions.

    ``positions`` defaults to ``offset + arange(seq)`` —
    ``position_offset`` is the context-parallel hook: cp rank r passes
    ``r * local_seq`` so its sequence chunk is rotated by GLOBAL
    positions (the same contract as the learned table's ``_pos_slice``,
    models/gpt.py).  Output dtype matches the input.
    """
    seq, d = x.shape[-2], x.shape[-1]
    if positions is None:
        positions = position_offset + jnp.arange(seq, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions, d, base)  # (seq, d/2) fp32
    return apply_rope_tables(x, cos, sin)


def apply_rope_tables(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotate by PRECOMPUTED (cos, sin) tables of shape (seq, d/2).

    Separate entry so callers scanning over layers (models/gpt.py) can
    compute the trig once and close over the tables — a scan body can't
    hoist the iota+trig itself, so the fused form would re-run it every
    layer and again in the remat backward."""
    d = x.shape[-1]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)

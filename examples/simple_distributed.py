"""Minimal data-parallel training loop — the "hello world" of the
framework (reference: examples/simple/distributed/
distributed_data_parallel.py: toy model + apex DDP + amp O1).

Runs anywhere: real TPU chips or virtual CPU devices
(XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).

    python examples/simple_distributed.py
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu._compat import shard_map
from apex_tpu.mlp import MLP
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state


def main():
    mesh = parallel_state.initialize_model_parallel()
    dp = mesh.shape["dp"]
    print(f"devices: {jax.device_count()}, dp={dp}")

    model = MLP([16, 32, 1], activation="relu")
    mp = amp.initialize(opt_level="O1")  # bf16-compute policy + scaler
    opt = FusedAdam(lr=1e-2)

    params = model.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    amp_state = mp.init()

    def train_step(params, opt_state, amp_state, x, y):
        def loss_fn(p):
            pred = model.apply(mp.policy.cast_to_compute(p), x)
            loss = jnp.mean((pred.astype(jnp.float32) - y) ** 2)
            return mp.scale_loss(amp_state, loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(params)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        grads, finite, new_amp = mp.unscale_and_adjust(amp_state, grads)
        new_params, new_opt = opt.step(
            opt_state, grads, params, grads_finite=finite
        )
        return new_params, new_opt, new_amp, jax.lax.pmean(loss, "dp")

    pspec = jax.tree.map(lambda _: P(), params)
    ospec = jax.tree.map(lambda _: P(), opt_state)
    aspec = jax.tree.map(lambda _: P(), amp_state)
    step = jax.jit(
        shard_map(
            train_step, mesh=mesh,
            in_specs=(pspec, ospec, aspec, P("dp"), P("dp")),
            out_specs=(pspec, ospec, aspec, P()),
        )
    )

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64 * dp, 16)).astype(np.float32))
    y = jnp.sum(x[:, :4], axis=1, keepdims=True)

    for i in range(200):
        params, opt_state, amp_state, loss = step(
            params, opt_state, amp_state, x, y
        )
        if i % 50 == 0:
            print(f"step {i:4d}  loss {float(loss):.5f}")
    print(f"final loss {float(loss):.5f}")
    assert float(loss) < 0.05, "did not converge"
    print("OK")


if __name__ == "__main__":
    main()

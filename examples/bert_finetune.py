"""BERT fine-tuning — sequence classification with the binary head.

The reference ships BERT only as a Megatron-toolkit test model; this is
the end-user walkthrough it implies: take the pretrained-style
`BertModel` (bidirectional encoder, [CLS] pooler, varlen attention
masks), put its 2-way head on a downstream classification task, and
fine-tune with the O4-analog policy (bf16 compute, fp32 params — the
usual fine-tuning precision).

Synthetic separable task by default: each "sentence" is classified by
whether its first real token falls in the upper half of the vocab, with
randomly padded lengths so the attention-mask/varlen path is genuinely
exercised.  Accuracy climbs from chance to ~100% in a few hundred
steps; swap :func:`synthetic_task` for a real tokenized dataset.

    python examples/bert_finetune.py --steps 200 --tp 2
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu._compat import shard_map
from apex_tpu.models import BertConfig, BertModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.telemetry.metrics import MetricsLogger, StepStats
from apex_tpu.telemetry.spans import phase
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.layers import state_specs_like


def synthetic_task(rng, n_batches, global_batch, seq, vocab):
    """Variable-length sequences; label = first token in upper vocab
    half.  Returns a list of (tokens, mask, labels)."""
    pool = []
    for _ in range(n_batches):
        tokens = rng.integers(1, vocab, (global_batch, seq))
        lengths = rng.integers(seq // 2, seq + 1, (global_batch,))
        mask = np.arange(seq)[None, :] < lengths[:, None]
        tokens = np.where(mask, tokens, 0)
        labels = (tokens[:, 0] >= vocab // 2).astype(np.int32)
        pool.append((jnp.asarray(tokens, jnp.int32),
                     jnp.asarray(mask),
                     jnp.asarray(labels, jnp.int32)))
    return pool


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--batch", type=int, default=4,
                    help="per-dp-rank batch rows")
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--eval-batches", type=int, default=4)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--opt-level", default="O4")
    ap.add_argument("--dp-ici-size", type=int, default=None,
                    help="hierarchical data parallelism: replicas per "
                         "fast-interconnect group (grad reduces run "
                         "RS(ici)->AR(dcn)->AG(ici))")
    ap.add_argument("--grad-compression", default="none",
                    choices=["none", "int8"],
                    help="int8-quantize the DCN leg of the hierarchical "
                         "gradient reduce (requires --dp-ici-size)")
    ap.add_argument("--compress-ici-legs", action="store_true",
                    help="ALSO int8-quantize the ICI RS/AG legs of "
                         "the hierarchical reduce (requires "
                         "--grad-compression int8)")
    ap.add_argument("--no-error-feedback", action="store_true")
    ap.add_argument("--zero3", "--param-shard", action="store_true",
                    dest="zero3",
                    help="full-parameter sharding (ZeRO-3/FSDP): "
                         "params live as 1-D fp32 shards over the "
                         "data axis, gathered per bucket on use; "
                         "grads reduce-scatter into the shard "
                         "(--bucket-mb sizes the gather buckets)")
    ap.add_argument("--fused-opt-tail", action="store_true",
                    help="one multi-tensor optimizer-tail pass over "
                         "packed buffers (bit-identical numerics; see "
                         "docs/optimizers.md)")
    ap.add_argument("--overlap-grad-sync", action="store_true",
                    help="bucket the hierarchical gradient reduce so "
                         "the scheduler can overlap the per-bucket "
                         "collectives (requires --dp-ici-size)")
    ap.add_argument("--bucket-mb", type=float, default=4.0,
                    help="bucket size in MiB for --overlap-grad-sync")
    ap.add_argument("--log-every", type=int, default=50,
                    help="telemetry flush cadence: loss/acc resolve "
                         "every N steps (no per-step host sync)")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="append structured step metrics here")
    args = ap.parse_args(argv)

    hier = args.dp_ici_size is not None
    if args.grad_compression != "none" and not hier:
        ap.error("--grad-compression requires --dp-ici-size")
    if args.overlap_grad_sync and not hier:
        ap.error("--overlap-grad-sync requires --dp-ici-size")
    if args.compress_ici_legs and args.grad_compression == "none":
        ap.error("--compress-ici-legs requires --grad-compression int8")
    if args.fused_opt_tail and args.tp > 1:
        ap.error("--fused-opt-tail needs replicated params (the "
                 "packed state cannot be tp-sharded; see "
                 "docs/optimizers.md)")
    if args.fused_opt_tail and args.zero3:
        ap.error("--fused-opt-tail packs replicated FusedAdam state; "
                 "--zero3 already runs the update on one flat sharded "
                 "buffer")
    bucket_bytes = int(args.bucket_mb * 1024 * 1024)
    comp = None
    if args.grad_compression != "none":
        from apex_tpu.ops.quantization import CompressionConfig

        comp = CompressionConfig(
            method=args.grad_compression,
            error_feedback=not args.no_error_feedback,
            ici_legs=args.compress_ici_legs,
        )
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=args.tp,
        data_parallel_ici_size_=args.dp_ici_size)
    data_axes = parallel_state.data_parallel_axis_names()
    dp = parallel_state.get_data_parallel_world_size()
    mp = amp.initialize(opt_level=args.opt_level)
    cfg = BertConfig(
        vocab_size=args.vocab, num_layers=args.layers,
        hidden_size=args.hidden, num_attention_heads=args.heads,
        max_position_embeddings=args.seq, policy=mp.policy,
        add_binary_head=True,
    )
    model = BertModel(cfg)
    specs = model.param_specs()
    params = model.init(jax.random.PRNGKey(0))
    if args.zero3:
        from apex_tpu.contrib.optimizers import (
            DistributedFusedAdam,
            reestablish_replicated,
        )

        opt = DistributedFusedAdam(
            lr=args.lr, param_specs=specs,
            axis_name=data_axes if hier else "dp",
            compression=comp, shard_params=True,
            bucket_bytes=bucket_bytes)
        opt.build_layout(params, mesh=mesh)
        shard_spec = opt.shard_spec(model_axes=("tp",))
        opt_specs = opt.state_specs(model_axes=("tp",))
        init_shards = jax.jit(shard_map(
            opt.init_shards, mesh=mesh, in_specs=(specs,),
            out_specs=shard_spec))
    else:
        opt = FusedAdam(lr=args.lr,
                        master_weights=mp.policy.master_weights,
                        fused_tail=args.fused_opt_tail)
        opt_state = opt.init(params)
        opt_specs = state_specs_like(specs, opt_state)

    def cls_loss(p, tokens, mask, labels):
        hidden = model.encode(p, tokens, attention_mask=mask)
        logits = model.binary_logits(p, hidden)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[:, None], 1)[:, 0]
        acc = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        return (jax.lax.pmean(jnp.mean(nll), data_axes),
                jax.lax.pmean(jnp.mean(acc), data_axes))

    # error-feedback residual state for the compressed reduce
    # (per-BUCKET residuals when the reduce is bucketed; under --zero3
    # the residuals ride the optimizer state instead)
    use_comm = (comp is not None and comp.error_feedback
                and not args.zero3)
    if use_comm:
        from apex_tpu.parallel.distributed import (
            comm_state_specs,
            init_comm_state,
        )

        if args.overlap_grad_sync:
            from apex_tpu.parallel import GradientBuckets

            plan = GradientBuckets.for_tree(
                params, bucket_bytes, param_specs=specs, mesh=mesh)
            comm_state = init_comm_state(
                params, data_axes, comp, mesh=mesh, param_specs=specs,
                buckets=plan)
            comm_specs = comm_state_specs(comm_state, data_axes,
                                          buckets=plan)
        else:
            comm_state = init_comm_state(
                params, data_axes, comp, mesh=mesh, param_specs=specs)
            comm_specs = comm_state_specs(comm_state, data_axes,
                                          param_specs=specs)
    else:
        comm_state, comm_specs = {}, {}

    def train_step(p, s, comm, tokens, mask, labels):
        # --zero3: p is the flat fp32 shard; gather-on-use rebuilds
        # the model-dtype weights per bucket inside the step
        if args.zero3:
            w, s = opt.gather_params(p, s)
            if args.tp > 1:
                w = reestablish_replicated(w, specs)
        else:
            w = p
        with phase("fwd_bwd"):
            (loss, acc), grads = jax.value_and_grad(
                cls_loss, has_aux=True)(w, tokens, mask, labels)
        if args.zero3:
            pass  # the optimizer's reduce-scatter IS the grad sync
        elif hier:
            from apex_tpu.parallel import all_reduce_gradients

            if use_comm:
                grads, comm = all_reduce_gradients(
                    grads, axis_name=data_axes, compression=comp,
                    comm_state=comm,
                    overlap_grad_sync=args.overlap_grad_sync,
                    bucket_bytes=bucket_bytes)
            else:
                grads = all_reduce_gradients(
                    grads, axis_name=data_axes, compression=comp,
                    overlap_grad_sync=args.overlap_grad_sync,
                    bucket_bytes=bucket_bytes)
        else:
            with phase("grad_sync"):
                grads = jax.tree.map(
                    lambda g: jax.lax.pmean(g, "dp"), grads)
        with phase("optimizer"):
            p, s = opt.step(s, grads, p)
        return p, s, comm, loss, acc

    data_spec = P(data_axes if hier else "dp")
    store_spec = shard_spec if args.zero3 else specs
    jstep = jax.jit(
        shard_map(
            train_step, mesh=mesh,
            in_specs=(store_spec, opt_specs, comm_specs,
                      data_spec, data_spec, data_spec),
            out_specs=(store_spec, opt_specs, comm_specs, P(), P()),
        ),
        donate_argnums=(0, 1),
    )

    def eval_fn(p, tokens, mask, labels):
        if args.zero3:
            p, _ = opt.gather_params(p)
            if args.tp > 1:
                p = reestablish_replicated(p, specs)
        return cls_loss(p, tokens, mask, labels)

    jeval = jax.jit(shard_map(
        eval_fn, mesh=mesh,
        in_specs=(store_spec, data_spec, data_spec, data_spec),
        out_specs=(P(), P()),
    ))

    place = lambda t, sp: jax.device_put(
        t, jax.tree.map(lambda s: NamedSharding(mesh, s), sp,
                        is_leaf=lambda x: isinstance(x, P)))
    if args.zero3:
        p = init_shards(place(params, specs))
        s = jax.jit(shard_map(
            opt.init, mesh=mesh, in_specs=(shard_spec,),
            out_specs=opt_specs))(p)
        jax.block_until_ready(p)
        del params  # the shards are the storage — drop the full tree
    else:
        p, s = place(params, specs), place(opt_state, opt_specs)
    cst = place(comm_state, comm_specs)
    global_batch = args.batch * dp
    rng = np.random.default_rng(0)
    # pool large enough that most of the vocab appears in position 0,
    # so eval measures the learned rule rather than memorized rows
    train_pool = synthetic_task(rng, 64, global_batch, args.seq,
                                args.vocab)
    eval_pool = synthetic_task(np.random.default_rng(1),
                               args.eval_batches, global_batch,
                               args.seq, args.vocab)

    # async harvesting: loss/acc stay device futures between flushes —
    # no per-step host sync; ms/step excludes the first-step compile
    # (stats.begin blocks on step 0, the clock starts after)
    stats = StepStats(tokens_per_step=global_batch, unit="seq")
    with MetricsLogger(jsonl_path=args.metrics_jsonl,
                       flush_every=args.log_every, stats=stats,
                       run="bert_finetune") as tlm:
        loss = acc = None
        for i in range(args.steps):
            tokens, mask, labels = train_pool[i % len(train_pool)]
            p, s, cst, loss, acc = jstep(p, s, cst, tokens, mask, labels)
            if i == 0:
                stats.begin((loss, acc))
            else:
                stats.tick()
            tlm.log_scalars(i, loss=loss, train_acc=acc)
        summary = stats.summary((loss, acc))
    if summary.get("timed_steps"):
        print(f"{summary['ms_per_step']:.1f} ms/step  "
              f"{summary['tokens_per_sec']:,.0f} seq/s")

    accs = [float(jeval(p, *b)[1]) for b in eval_pool]
    print(f"eval accuracy: {np.mean(accs):.3f}")
    return {"eval_accuracy": float(np.mean(accs))}


if __name__ == "__main__":
    main()

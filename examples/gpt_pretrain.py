"""GPT pretraining — the full production stack in one script.

The "switch from the reference and find everything" walkthrough: what
`apex.amp` + `apex.transformer` + `apex.contrib.optimizers` users
assemble from Megatron pieces, wired TPU-native end to end:

- 4-axis mesh (``dp x pp x cp x tp``) from one initialize call;
- precision `Policy` driving every dtype through one config kwarg
  (O5 bf16 default; pass ``--opt-level O2`` for fp16 + dynamic scaler);
- the dispatched 1F1B pipeline schedule (``pipeline_1f1b_grads``) with
  microbatch gradient accumulation;
- FusedAdam with fp32 masters, ``--zero`` for the reduce-scatter /
  all-gather sharded ``DistributedFusedAdam``, or ``--zero3`` for
  FULL-parameter sharding (gather-on-use weights, sharded update, no
  replicated copy — the h≥4096-class memory unlock);
- dynamic loss scaling with model-parallel overflow consensus (fp16
  levels only — bf16 needs none);
- async, atomic checkpointing + SIGTERM-safe autoresume;
- structured telemetry (apex_tpu.telemetry): the loss is held as an
  unresolved device future and resolved only at the ``--log-every``
  flush cadence — NO per-step ``float(loss)`` host sync, so XLA's
  async dispatch stays ahead of the host — with live tokens/s + MFU,
  subsystem events (checkpoint/guard/comm) in the ``--metrics-jsonl``
  stream, phase-annotated traces and an on-demand trace trigger
  (touch ``<--trace-dir>/TRACE_REQUEST`` mid-run).

Synthetic token stream by default; swap :func:`batches` for a real
tokenized corpus.

    python examples/gpt_pretrain.py --tp 2 --pp 2 --num-micro 4 \
        --steps 50 --checkpoint-dir /tmp/gpt_ck
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu._compat import shard_map
from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.telemetry.metrics import (
    MetricsLogger,
    StepStats,
    transformer_flops_per_token,
)
from apex_tpu.telemetry.spans import TraceTrigger, phase
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.amp import model_parallel_all_finite
from apex_tpu.transformer.tensor_parallel.layers import state_specs_like
from apex_tpu.transformer.tensor_parallel import clip_grad_norm
from apex_tpu.utils.autoresume import AutoResume


def batches(rng, n_batches, global_batch, seq, vocab):
    """Pre-generated synthetic LM batches (see --data for a corpus)."""
    pool = []
    for _ in range(n_batches):
        tokens = jnp.asarray(
            rng.integers(0, vocab, (global_batch, seq)), jnp.int32)
        pool.append((tokens, jnp.roll(tokens, -1, axis=1)))
    return pool


def file_batches(path, n_batches, global_batch, seq, vocab):
    """Real-corpus pool from an apex_tpu.data mmap token file: windows
    via IndexedTokenDataset, order via MegatronPretrainingSampler (the
    whole global batch is materialized here and dp-sharded by the
    step's P("dp") in_spec, so the sampler runs as one logical rank)."""
    from apex_tpu.data import IndexedTokenDataset, pretraining_batches
    from apex_tpu.transformer.data import MegatronPretrainingSampler

    ds = IndexedTokenDataset(path, seq_len=seq)
    if ds.max_token >= vocab:
        raise ValueError(
            f"{path}: corpus max token id {ds.max_token} >= model vocab "
            f"{vocab} — out-of-range ids would train on clamped/masked "
            f"embeddings silently")
    sampler = MegatronPretrainingSampler(
        total_samples=len(ds), consumed_samples=0,
        micro_batch_size=global_batch,
        data_parallel_rank=0, data_parallel_size=1,
    )
    pool = []
    for toks, tgts in pretraining_batches(ds, sampler):
        pool.append((jnp.asarray(toks), jnp.asarray(tgts)))
        if len(pool) >= n_batches:
            break
    if not pool:
        raise ValueError(f"{path}: fewer than {global_batch} windows")
    return pool


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--num-micro", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--micro-batch", type=int, default=2,
                    help="per-dp-rank microbatch rows")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--opt-level", default="O5",
                    help="O0..O5 — fp16 levels add dynamic loss scaling")
    ap.add_argument("--zero", action="store_true",
                    help="shard optimizer state over dp "
                         "(DistributedFusedAdam)")
    ap.add_argument("--zero3", "--param-shard", action="store_true",
                    dest="zero3",
                    help="FULL-parameter sharding (ZeRO-3/FSDP): "
                         "params live as 1-D fp32 shards over the "
                         "data axis and are all-gathered to model "
                         "dtype per bucket ON USE (--bucket-mb sizes "
                         "the buckets); grads reduce-scatter straight "
                         "into the shard and the update runs there — "
                         "per-device state bytes drop ~world-fold, "
                         "unlocking models replicated DDP cannot "
                         "hold.  Checkpoints store the shard buffer "
                         "(resume at the same dp topology; "
                         "see docs/distributed.md)")
    ap.add_argument("--dp-ici-size", type=int, default=None,
                    help="split data parallelism into a (dcn, ici) "
                         "hierarchy with this many replicas per "
                         "fast-interconnect group; gradient reduces "
                         "then run RS(ici)->AR(dcn)->AG(ici) so only "
                         "1/ici of the bytes cross the slow axis")
    ap.add_argument("--grad-compression", default="none",
                    choices=["none", "int8"],
                    help="quantize the DCN leg of the hierarchical "
                         "gradient reduce (requires --dp-ici-size); "
                         "ICI legs and gradient dtypes are untouched")
    ap.add_argument("--compression-block", type=int, default=256,
                    help="elements per fp32 scale in the quantized leg")
    ap.add_argument("--compression-rounding", default="nearest",
                    choices=["nearest", "stochastic"])
    ap.add_argument("--compress-ici-legs", action="store_true",
                    help="ALSO int8-quantize the ICI reduce-scatter/"
                         "all-gather legs of the hierarchical reduce "
                         "(EQuARX's ICI half; requires "
                         "--grad-compression int8) — ~4x fewer bytes "
                         "on the fast links too")
    ap.add_argument("--no-error-feedback", action="store_true",
                    help="drop the quantization-residual compensation "
                         "state (lossier; mainly for A/B experiments)")
    ap.add_argument("--fused-opt-tail", action="store_true",
                    help="run the optimizer tail as ONE multi-tensor "
                         "pass over bucketed buffers (moments/masters "
                         "stored packed — bit-identical numerics, "
                         "fewer HBM passes; checkpoints are NOT "
                         "layout-compatible with the per-leaf state). "
                         "FusedAdam path only (--zero shards its own "
                         "flat buffer already)")
    ap.add_argument("--exp-avg-sq-dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="storage dtype of Adam's second moment "
                         "(bfloat16 halves its bytes in the fused "
                         "tail; math stays fp32 — see "
                         "docs/optimizers.md for when it is safe)")
    ap.add_argument("--overlap-grad-sync", action="store_true",
                    help="bucket the hierarchical gradient reduce "
                         "(reverse-layer order) so the scheduler can "
                         "overlap the per-bucket collectives with "
                         "surrounding compute (requires --dp-ici-size; "
                         "see docs/distributed.md)")
    ap.add_argument("--bucket-mb", type=float, default=4.0,
                    help="bucket size in MiB for --overlap-grad-sync "
                         "(the reference's message_size analog)")
    ap.add_argument("--num-experts", type=int, default=None,
                    help="Switch-MoE experts riding dp as the ep axis")
    ap.add_argument("--position-embedding", default="learned",
                    choices=["learned", "rope"],
                    help="rope = rotary (q, k) rotation, no position "
                         "table; any sequence length runs")
    ap.add_argument("--activation", default="gelu",
                    choices=["gelu", "swiglu"])
    ap.add_argument("--normalization", default="layernorm",
                    choices=["layernorm", "rmsnorm"])
    ap.add_argument("--clip-grad", type=float, default=None,
                    help="global-norm gradient clipping (mesh-aware)")
    ap.add_argument("--data", default=None,
                    help="apex_tpu.data token file (write_token_file); "
                         "synthetic stream when omitted")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--save-every", type=int, default=25)
    ap.add_argument("--log-every", type=int, default=10,
                    help="telemetry flush cadence: device scalars "
                         "(loss) resolve and print every N steps — the "
                         "ONLY per-step host sync knob (1 = the old "
                         "synchronous behaviour)")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="append structured step metrics + subsystem "
                         "events here (tools/metrics_report.py reads "
                         "it)")
    ap.add_argument("--trace-dir", default=None,
                    help="arm the on-demand trace trigger: touch "
                         "<trace-dir>/TRACE_REQUEST mid-run to capture "
                         "an xplane window (APEX_TPU_TRACE_STEPS "
                         "steps) without restarting")
    ap.add_argument("--watchdog-s", type=float, default=None,
                    help="stall watchdog deadline in seconds (dumps "
                         "all-thread stacks on heartbeat silence; "
                         "heartbeats mirror to "
                         "$APEX_TPU_HEARTBEAT_FILE for tpu_watch)")
    args = ap.parse_args(argv)

    hier = args.dp_ici_size is not None
    any_zero = args.zero or args.zero3
    if args.zero and args.zero3:
        ap.error("--zero and --zero3 are one knob at two depths: "
                 "state sharding vs full parameter sharding — pick "
                 "one")
    if args.zero3 and args.num_experts:
        ap.error("--zero3 cannot shard data-axis-sharded expert "
                 "leaves (they have no replicated copy to re-shard); "
                 "use --zero for MoE")
    if args.grad_compression != "none" and not hier:
        ap.error("--grad-compression quantizes the DCN leg of the "
                 "hierarchical reduce: it requires --dp-ici-size")
    if args.overlap_grad_sync and not hier:
        ap.error("--overlap-grad-sync buckets the hierarchical data "
                 "sync: it requires --dp-ici-size")
    if args.overlap_grad_sync and any_zero:
        ap.error("--overlap-grad-sync applies to the DDP reduce; "
                 "--zero/--zero3 replace it with the sharded "
                 "optimizer's reduce-scatter")
    if args.fused_opt_tail and any_zero:
        ap.error("--fused-opt-tail packs the replicated FusedAdam "
                 "state; --zero/--zero3 already run the update on "
                 "one flat sharded buffer")
    if args.fused_opt_tail and (args.pp > 1 or args.tp > 1
                                or args.num_experts):
        ap.error("--fused-opt-tail needs replicated params: the "
                 "packed state buffers concatenate leaves across "
                 "bucket boundaries and cannot be sharded over "
                 "pp/tp/ep axes (see docs/optimizers.md) — drop the "
                 "flag or the model-parallel axes")
    bucket_bytes = int(args.bucket_mb * 1024 * 1024)
    if hier and args.num_experts:
        ap.error("--dp-ici-size is incompatible with --num-experts "
                 "(experts ride the dp axis, which the hierarchical "
                 "layout keeps at size 1)")
    if args.compress_ici_legs and args.grad_compression == "none":
        ap.error("--compress-ici-legs extends --grad-compression int8 "
                 "to the ICI legs: enable int8 first")
    comp = None
    if args.grad_compression != "none":
        from apex_tpu.ops.quantization import CompressionConfig

        comp = CompressionConfig(
            method=args.grad_compression,
            block_size=args.compression_block,
            rounding=args.compression_rounding,
            error_feedback=not args.no_error_feedback,
            ici_legs=args.compress_ici_legs,
        )
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=args.tp,
        pipeline_model_parallel_size_=args.pp,
        data_parallel_ici_size_=args.dp_ici_size,
    )
    data_axes = parallel_state.data_parallel_axis_names()
    dp = parallel_state.get_data_parallel_world_size()
    mp = amp.initialize(opt_level=args.opt_level)
    cfg = GPTConfig(
        vocab_size=args.vocab, num_layers=args.layers,
        hidden_size=args.hidden, num_attention_heads=args.heads,
        max_position_embeddings=args.seq, policy=mp.policy,
        position_embedding=args.position_embedding,
        activation=args.activation,
        normalization=args.normalization,
        num_experts=args.num_experts,
        moe_capacity_factor=2.0,  # read only when num_experts is set
    )
    model = GPTModel(cfg)
    pp_path = args.pp > 1
    specs = model.pipeline_param_specs() if pp_path else model.param_specs()
    params = model.init(jax.random.PRNGKey(0))
    use_scaler = mp.policy.loss_scale is not None
    amp_state = mp.init()

    place = lambda t, sp: jax.device_put(
        t, jax.tree.map(lambda s: NamedSharding(mesh, s), sp,
                        is_leaf=lambda x: isinstance(x, P)))

    if any_zero:
        from apex_tpu.contrib.optimizers import (
            DistributedFusedAdam,
            reestablish_replicated,
        )

        # param_specs routes MoE expert leaves (dp-sharded as ep)
        # through the rank-local update instead of the flat RS/AG.
        # Hierarchical: RS rides ici, the 1/ici shard all-reduces
        # across dcn (int8-quantized when --grad-compression is set,
        # residual state inside the optimizer state).  --zero3
        # additionally shards the PARAMS: they live as the flat fp32
        # shard and are gathered per bucket on use inside the step
        # (int8 gather under --compress-ici-legs)
        opt = DistributedFusedAdam(
            lr=args.lr, param_specs=specs,
            axis_name=data_axes if hier else "dp",
            compression=comp,
            shard_params=args.zero3,
            bucket_bytes=bucket_bytes,
        )
        if args.zero3:
            opt.build_layout(params, mesh=mesh)
            shard_spec = opt.shard_spec(model_axes=("pp", "tp"))
            init_shards = jax.jit(shard_map(
                opt.init_shards, mesh=mesh, in_specs=(specs,),
                out_specs=shard_spec))
            opt_specs = opt.state_specs(model_axes=("pp", "tp"))
            init_opt = jax.jit(shard_map(
                opt.init, mesh=mesh, in_specs=(shard_spec,),
                out_specs=opt_specs))
        else:
            opt_specs = opt.state_specs(model_axes=("pp", "tp"))
            init_opt = jax.jit(shard_map(
                opt.init, mesh=mesh, in_specs=(specs,),
                out_specs=opt_specs))
    else:
        # --fused-opt-tail: moments + masters live as packed bucket
        # buffers and the whole clip→adam→cast chain is one pass per
        # buffer (bit-identical at fp32 moments; see docs/optimizers.md)
        opt = FusedAdam(lr=args.lr,
                        master_weights=mp.policy.master_weights,
                        fused_tail=args.fused_opt_tail,
                        exp_avg_sq_dtype=jnp.dtype(args.exp_avg_sq_dtype))
        opt_state = opt.init(params)
        opt_specs = state_specs_like(specs, opt_state)

    # comm state for the compressed DDP reduce: error-feedback
    # residuals, and the step counter stochastic rounding derives its
    # per-step key from (ZeRO carries its own inside the optimizer
    # state)
    use_comm = (comp is not None and not any_zero
                and (comp.error_feedback
                     or comp.rounding == "stochastic"))
    if use_comm:
        from apex_tpu.parallel.distributed import (
            comm_state_specs,
            init_comm_state,
        )

        if args.overlap_grad_sync:
            # per-BUCKET residuals matching the bucketed reduce; the
            # plan must see the same leaf shapes/dtypes and bucket
            # size the in-step reduce derives its own plan from
            from apex_tpu.parallel import GradientBuckets

            plan = GradientBuckets.for_tree(
                params, bucket_bytes, param_specs=specs, mesh=mesh)
            comm_state = init_comm_state(
                params, data_axes, comp, mesh=mesh, param_specs=specs,
                buckets=plan)
            comm_specs = comm_state_specs(comm_state, data_axes,
                                          buckets=plan)
        else:
            comm_state = init_comm_state(
                params, data_axes, comp, mesh=mesh, param_specs=specs)
            comm_specs = comm_state_specs(comm_state, data_axes,
                                          param_specs=specs)
    else:
        comm_state, comm_specs = {}, {}

    def train_step(params, opt_state, amp_state, comm_state,
                   tokens, targets):
        # --zero3: ``params`` is the flat fp32 shard; gather-on-use
        # rebuilds the model-dtype tree per bucket (tlm.param_gather
        # scopes inside), advancing the ag residual when the gather is
        # int8 + error feedback.  The replicated-typed invariant over
        # pp/tp is re-established for the pipeline/TP collectives.
        if args.zero3:
            weights, opt_state = opt.gather_params(params, opt_state)
            if args.pp > 1 or args.tp > 1:
                weights = reestablish_replicated(weights, specs)
        else:
            weights = params
        # tlm.* phase scopes: xprof segments the compiled step's
        # timeline by phase (fwd_bwd / grad_sync / optimizer) instead
        # of by mangled fusion names — see docs/observability.md
        with phase("fwd_bwd"):
            if pp_path:
                loss, grads = model.pipeline_1f1b_grads(
                    weights, tokens, targets, args.num_micro)
                if use_scaler:
                    # fp16 + pipeline: scale the already-computed grads
                    # so the scaler's overflow-skip + adjustment state
                    # machine runs (infs survive finite scaling).  This
                    # protects against overflow but NOT bwd underflow —
                    # the bf16 levels (the TPU default) are the
                    # recommended pipeline precision and need no scaler
                    # at all
                    s = amp_state.scaler_states[0].loss_scale
                    grads = jax.tree.map(
                        lambda g: g * s.astype(g.dtype), grads)
            else:
                def loss_fn(p):
                    loss = model.loss(p, tokens, targets)
                    return mp.scale_loss(amp_state, loss), loss

                grads, loss = jax.grad(loss_fn, has_aux=True)(weights)
                loss = jax.lax.pmean(loss, "dp")
        if not pp_path and not any_zero and not hier:
            # spec-aware dp sync: replicated leaves pmean (a no-op
            # re-establishing invariance — model.loss's internal
            # pmean already made their grads globally complete);
            # dp-SHARDED leaves (MoE experts riding dp as ep) are
            # already final via the all_to_all transpose and must
            # NOT be averaged elementwise across unrelated experts.
            # ZeRO skips this: its reduce-scatter is the reduction
            from apex_tpu.transformer.parallel_state import (
                spec_axis_names,
            )

            with phase("grad_sync"):
                grads = jax.tree.map(
                    lambda g, sp: (g if "dp" in spec_axis_names(sp)
                                   else jax.lax.pmean(g, "dp")),
                    grads, specs,
                )
        if hier:
            # the dummy "dp" axis made every model-internal dp reduce a
            # no-op: the data-axis loss mean happens here instead
            loss = jax.lax.pmean(loss, data_axes)
        if use_scaler:
            # MoE: expert grads differ per dp rank, so the overflow
            # verdict must ALSO reach dp consensus or ranks would skip
            # steps independently and desync replicated params.
            # Hierarchical: grads are not data-synced until after the
            # unscale (below), so the verdict must span the data axes —
            # doubly so with compression, which scrambles infs
            axes = ("tp", "pp")
            if args.num_experts:
                axes += ("dp",)
            if hier:
                axes += data_axes
            grads, finite, amp_state = mp.unscale_and_adjust(
                amp_state, grads,
                finite_reduce=lambda f: model_parallel_all_finite(
                    f, axis_names=axes))
        else:
            finite = None
        new_comm = comm_state
        if hier and not any_zero:
            # data sync AFTER the unscale: the compressed reduce sees
            # true-magnitude grads (the error-feedback residual is then
            # consistent across dynamic loss-scale changes), RS rides
            # ici, only the 1/ici chunk crosses dcn (int8 + fp32
            # scales when compressed)
            from apex_tpu.parallel import all_reduce_gradients

            if use_comm:
                grads, new_comm = all_reduce_gradients(
                    grads, axis_name=data_axes, compression=comp,
                    comm_state=comm_state,
                    overlap_grad_sync=args.overlap_grad_sync,
                    bucket_bytes=bucket_bytes)
                if finite is not None:
                    # a skipped (overflowed) step must not absorb
                    # garbage into the residual
                    from apex_tpu.optimizers.base import tree_where

                    new_comm = tree_where(finite, new_comm, comm_state)
            else:
                grads = all_reduce_gradients(
                    grads, axis_name=data_axes, compression=comp,
                    overlap_grad_sync=args.overlap_grad_sync,
                    bucket_bytes=bucket_bytes)
        with phase("optimizer"):
            if args.clip_grad is not None:
                # AFTER unscale (clip sees true-magnitude grads),
                # BEFORE the optimizer; duplicate-aware over the mesh
                # (tp/pp shards + expert-dp leaves psum, replicated
                # leaves count once)
                grads, _ = clip_grad_norm(grads, specs, args.clip_grad)
            if args.zero3:
                # grads reduce-scatter straight into the shard; the
                # update runs there and NOTHING gathers back — the
                # next step's gather-on-use is the gather
                new_params, new_opt = opt.step(
                    opt_state, grads, params, grads_finite=finite)
            elif args.zero:
                # expert grads are optimizer-ready in BOTH paths here:
                # the pipeline's data_reduce applies the 1/n itself,
                # and the pp=1 path's model.loss pmeans the loss inside
                # the differentiated function (the all_to_all transpose
                # then delivers the final global-mean gradient) — so
                # the local path must not divide again
                new_params, new_opt = opt.step(
                    opt_state, grads, params, grads_finite=finite,
                    local_grads_prenormalized=True)
                new_params = reestablish_replicated(new_params, specs)
            else:
                new_params, new_opt = opt.step(
                    opt_state, grads, params, grads_finite=finite)
        return new_params, new_opt, amp_state, new_comm, loss

    amp_specs = jax.tree.map(lambda _: P(), amp_state)
    data_spec = P(data_axes if hier else "dp")
    # the threaded "params" are the flat shard under --zero3 — the
    # replicated tree never exists between steps
    store_spec = shard_spec if args.zero3 else specs
    step = jax.jit(
        shard_map(
            train_step, mesh=mesh,
            in_specs=(store_spec, opt_specs, amp_specs, comm_specs,
                      data_spec, data_spec),
            out_specs=(store_spec, opt_specs, amp_specs, comm_specs,
                       P()),
        ),
        donate_argnums=(0, 1),
    )

    n_params = sum(int(np.prod(jnp.shape(l)))
                   for l in jax.tree.leaves(params))
    placed = place(params, specs)
    if args.zero3:
        # the shards are the storage from here on: drop the replicated
        # init tree, or a full param copy stays pinned all run and the
        # ~world-fold persistent-bytes win never materializes
        placed = init_shards(placed)
        jax.block_until_ready(placed)
        del params
    start = 0
    ar = None
    restored = None
    if args.checkpoint_dir:
        ar = AutoResume(args.checkpoint_dir,
                        interval_steps=args.save_every,
                        install_sigterm_handler=True)
        restored, start = ar.resume()
        if restored is not None:
            # --zero3 checkpoints hold the flat shard buffer (1/world
            # the bytes of the replicated tree); resume at the same
            # data-parallel topology
            placed = place(restored["params"], store_spec)
            amp_state = mp.load_state_dict(restored["amp"])
            if use_comm and "comm" in restored:
                # resumed error-feedback residuals keep the
                # quantization compensation instead of re-zeroing it
                comm_state = restored["comm"]
            start += 1  # the saved step already ran
            print(f"resuming after step {start - 1}")
    # optimizer state AFTER the resume decision, so a restored run
    # never reverts to freshly-initialised masters
    if any_zero:
        opt_state = (place(restored["opt"], opt_specs)
                     if restored is not None and "opt" in restored
                     else init_opt(placed))
    else:
        opt_state = (place(restored["opt"], opt_specs)
                     if restored is not None and "opt" in restored
                     else place(opt_state, opt_specs))

    comm_state = place(comm_state, comm_specs)
    global_batch = args.micro_batch * args.num_micro * dp
    pool = (file_batches(args.data, 8, global_batch, args.seq, args.vocab)
            if args.data else
            batches(np.random.default_rng(0), 8, global_batch,
                    args.seq, args.vocab))

    # telemetry: loss stays an unresolved device future between
    # flushes; tokens/s + MFU come from the same FLOP model bench.py /
    # tools/scale_mfu.py report, timed from AFTER the first step so the
    # XLA compile never pollutes ms/step
    stats = StepStats(
        tokens_per_step=global_batch * args.seq,
        flops_per_token=transformer_flops_per_token(
            n_params, args.layers, args.hidden, args.seq),
    )
    tlm = MetricsLogger(jsonl_path=args.metrics_jsonl,
                        flush_every=args.log_every, stats=stats,
                        run="gpt_pretrain")
    tlm.attach_events()  # checkpoint/comm/guard events join the stream
    trig = TraceTrigger(trace_dir=args.trace_dir) \
        if (args.trace_dir or os.environ.get("APEX_TPU_TRACE_DIR")) \
        else None
    wd = None
    if args.watchdog_s:
        from apex_tpu.resilience import Watchdog

        wd = Watchdog(deadline_s=args.watchdog_s).start()
    loss = jnp.float32(float("nan"))
    try:
        for i in range(start, args.steps):
            with tlm.timing("data"):
                tokens, targets = pool[i % len(pool)]
            placed, opt_state, amp_state, comm_state, loss = step(
                placed, opt_state, amp_state, comm_state, tokens, targets)
            if i == start:
                stats.begin(loss)  # blocks once: compile excluded
            else:
                stats.tick()
            tlm.log_scalars(i, loss=loss)  # async: resolves at cadence
            if trig is not None:
                trig.poll(i)
            if wd is not None:
                wd.beat(step=i)
            if ar is not None:
                # build the (expensive, device_get-ing) state dict only
                # on ticks maybe_save would actually write
                due = (i > 0 and i % args.save_every == 0) \
                    or ar.termination_requested() or i == args.steps - 1
                if due:
                    with tlm.timing("checkpoint"), phase("checkpoint"):
                        state = {"params": jax.device_get(placed),
                                 "opt": jax.device_get(opt_state),
                                 "amp": mp.state_dict(amp_state),
                                 "step": np.int64(i)}
                        if use_comm:
                            state["comm"] = jax.device_get(comm_state)
                        saved = ar.maybe_save(i, state,
                                              force=(i == args.steps - 1))
                    if saved and ar.termination_requested():
                        print("termination requested; checkpoint saved")
                        return {"loss": float(loss), "stopped_at": i}
        summary = stats.summary(loss)  # blocks on the final step
        tlm.flush()
        if summary.get("timed_steps"):
            line = (f"{summary['ms_per_step']:.1f} ms/step  "
                    f"{summary['tokens_per_sec']:,.0f} tokens/s")
            if "mfu" in summary:
                line += f"  mfu {summary['mfu']:.3f}"
            print(line)
        return {"loss": float(loss), "params": placed}
    finally:
        if wd is not None:
            wd.stop()
        if trig is not None:
            trig.close()
        tlm.close()  # flushes, deregisters the event sink, closes fd


if __name__ == "__main__":
    main()

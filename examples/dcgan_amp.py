"""DCGAN with multiple models, optimizers, and loss scalers
(reference: examples/dcgan/main_amp.py:214-253 — the multi-loss amp
workflow: amp.initialize([netD, netG], [optD, optG], num_losses=3) and
three scale_loss ids for errD_real, errD_fake, errG).

Synthetic data; small nets so it runs on CPU devices too.

    python examples/dcgan_amp.py --steps 50
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam

IMG = 16
LATENT = 32


def init_g(key):
    k1, k2 = jax.random.split(key)
    return {
        "fc": 0.05 * jax.random.normal(k1, (LATENT, 256)),
        "out": 0.05 * jax.random.normal(k2, (256, IMG * IMG)),
    }


def init_d(key):
    k1, k2 = jax.random.split(key)
    return {
        "fc": 0.05 * jax.random.normal(k1, (IMG * IMG, 256)),
        "out": 0.05 * jax.random.normal(k2, (256, 1)),
    }


def gen(params, z):
    h = jax.nn.leaky_relu(z @ params["fc"])
    return jnp.tanh(h @ params["out"])


def disc(params, x):
    h = jax.nn.leaky_relu(x @ params["fc"])
    return (h @ params["out"])[:, 0]


def bce(logits, target):
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * target
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()

    # one MixedPrecision handle, three loss ids — errD_real, errD_fake,
    # errG — exactly the reference's per-loss scaler setup
    mp = amp.initialize(opt_level="O1", num_losses=3)
    amp_state = mp.init()
    opt_d, opt_g = FusedAdam(lr=2e-4), FusedAdam(lr=2e-4)

    params_d, params_g = init_d(jax.random.PRNGKey(0)), init_g(
        jax.random.PRNGKey(1)
    )
    opt_state_d, opt_state_g = opt_d.init(params_d), opt_g.init(params_g)

    @jax.jit
    def train_step(params_d, params_g, opt_state_d, opt_state_g,
                   amp_state, real, z, z2):
        # --- D step: two losses, two scalers ------------------------
        def d_loss_real(pd):
            return mp.scale_loss(amp_state, bce(disc(pd, real), 1.0), 0)

        def d_loss_fake(pd):
            fake = gen(params_g, z)
            return mp.scale_loss(
                amp_state, bce(disc(pd, jax.lax.stop_gradient(fake)), 0.0), 1
            )

        g_real = jax.grad(d_loss_real)(params_d)
        g_fake = jax.grad(d_loss_fake)(params_d)
        g_real, fin0, amp_state = mp.unscale_and_adjust(amp_state, g_real, 0)
        g_fake, fin1, amp_state = mp.unscale_and_adjust(amp_state, g_fake, 1)
        grads_d = jax.tree.map(jnp.add, g_real, g_fake)
        params_d, opt_state_d = opt_d.step(
            opt_state_d, grads_d, params_d, grads_finite=fin0 & fin1
        )

        # --- G step: third scaler ------------------------------------
        def g_loss(pg):
            return mp.scale_loss(
                amp_state, bce(disc(params_d, gen(pg, z2)), 1.0), 2
            )

        grads_g = jax.grad(g_loss)(params_g)
        grads_g, fin2, amp_state = mp.unscale_and_adjust(amp_state, grads_g, 2)
        params_g, opt_state_g = opt_g.step(
            opt_state_g, grads_g, params_g, grads_finite=fin2
        )
        return params_d, params_g, opt_state_d, opt_state_g, amp_state

    rng = np.random.default_rng(0)
    for i in range(args.steps):
        real = jnp.asarray(
            rng.normal(size=(args.batch, IMG * IMG)).astype(np.float32)
        )
        z = jnp.asarray(rng.normal(size=(args.batch, LATENT)).astype(np.float32))
        z2 = jnp.asarray(rng.normal(size=(args.batch, LATENT)).astype(np.float32))
        params_d, params_g, opt_state_d, opt_state_g, amp_state = train_step(
            params_d, params_g, opt_state_d, opt_state_g, amp_state,
            real, z, z2,
        )
    scales = [float(s.loss_scale) for s in amp_state.scaler_states]
    print(f"done {args.steps} steps; loss scales: {scales}")
    print("OK")


if __name__ == "__main__":
    main()

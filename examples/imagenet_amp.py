"""ImageNet-style ResNet trainer — the flagship integration example
(reference: examples/imagenet/main_amp.py:73-190: RN50 + amp O2 + DDP +
SyncBN + eval with prec@1/5 + checkpoint/resume + best-model tracking).

Feature-for-feature with the reference trainer, TPU-native:

- O2-analog mixed precision: bf16 compute inside the model, fp32 master
  weights in FusedSGD, BN statistics in fp32 synchronized over the "dp"
  mesh axis (the model's built-in SyncBN — reference's
  ``parallel.SyncBatchNorm`` + ``--sync_bn``);
- training epochs with running loss / prec@1 / prec@5 meters;
- a validation pass computing prec@1 / prec@5
  (reference: main_amp.py ``validate`` + ``accuracy``);
- checkpoint save every epoch via :mod:`apex_tpu.checkpoint` (manifest +
  flat blob through the C++ flatten), best-model tracking
  (``best.ckpt``), and ``--resume`` restoring params, optimizer,
  BN stats, epoch counter and best-prec@1 exactly
  (reference: main_amp.py checkpoint dict + ``--resume`` branch);
- ``--evaluate`` runs validation only;
- pluggable data: synthetic batches by default so the example runs
  anywhere; replace :func:`synthetic_batches` with a real input
  pipeline for actual training.

    python examples/imagenet_amp.py --depth 50 --batch-size 32 \
        --epochs 2 --steps-per-epoch 20 --checkpoint-dir /tmp/rn50
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import checkpoint
from apex_tpu._compat import shard_map
from apex_tpu.models.resnet import ResNet, ResNetConfig
from apex_tpu.optimizers import FusedSGD
from apex_tpu.telemetry.metrics import MetricsLogger, StepStats
from apex_tpu.transformer import parallel_state


def synthetic_pool(seed, n_batches, global_batch, image_size, num_classes):
    """Deterministic synthetic dataset: ``n_batches`` pre-generated
    ``(images, labels)`` pairs — the pluggable data source.

    Pre-generating keeps host-side RNG out of the timed training loop
    (the device step, not numpy, is what the img/s figure measures) and
    gives validation a FIXED set so prec@1 is comparable across epochs,
    like the reference's val loader.  Swap for a real pipeline yielding
    ``images: (global_batch, H, W, 3) float32`` NHWC and
    ``labels: (global_batch,) int32``."""
    rng = np.random.default_rng(seed)
    pool = []
    for _ in range(n_batches):
        images = jnp.asarray(rng.normal(
            size=(global_batch, image_size, image_size, 3)
        ).astype(np.float32))
        labels = jnp.asarray(
            rng.integers(0, num_classes, (global_batch,)), jnp.int32
        )
        pool.append((images, labels))
    return pool


def _topk_correct(logits, labels):
    """(#top1-correct, #top5-correct) on the local shard — psum'd by the
    caller (reference: main_amp.py ``accuracy(output, target, topk=(1,5))``)."""
    top5 = jax.lax.top_k(logits, 5)[1]
    hit = top5 == labels[:, None]
    return (
        jnp.sum(hit[:, 0].astype(jnp.float32)),
        jnp.sum(jnp.any(hit, axis=1).astype(jnp.float32)),
    )


def build_steps(model, opt, num_classes, mesh, param_tree, opt_tree,
                stats_tree):
    """Compile the train and eval steps once; both return meter updates."""
    to_spec = lambda tree: jax.tree.map(lambda _: P(), tree)
    pspec, ospec, sspec = (to_spec(param_tree), to_spec(opt_tree),
                           to_spec(stats_tree))

    def train_step(params, opt_state, bn_stats, images, labels):
        def loss_fn(p, stats):
            logits, new_stats = model.apply(p, stats, images, training=True)
            one_hot = jax.nn.one_hot(labels, num_classes)
            loss = -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1)
            )
            return loss, (new_stats, logits)

        (loss, (new_stats, logits)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, bn_stats)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        # running BN stats averaged over dp (activations were already
        # SyncBN-normalized inside apply; this keeps the saved stats
        # identical on every rank)
        new_stats = jax.tree.map(
            lambda s: jax.lax.pmean(s, "dp"), new_stats
        )
        new_params, new_opt = opt.step(opt_state, grads, params)
        c1, c5 = _topk_correct(logits, labels)
        n = jnp.float32(labels.shape[0])
        meters = jax.lax.psum(jnp.stack([c1, c5, n]), "dp")
        return (new_params, new_opt, new_stats,
                jax.lax.pmean(loss, "dp"), meters)

    def eval_step(params, bn_stats, images, labels):
        logits, _ = model.apply(params, bn_stats, images, training=False)
        one_hot = jax.nn.one_hot(labels, num_classes)
        loss = -jnp.mean(
            jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1)
        )
        c1, c5 = _topk_correct(logits, labels)
        n = jnp.float32(labels.shape[0])
        return (jax.lax.pmean(loss, "dp"),
                jax.lax.psum(jnp.stack([c1, c5, n]), "dp"))

    train = jax.jit(
        shard_map(
            train_step, mesh=mesh,
            in_specs=(pspec, ospec, sspec, P("dp"), P("dp")),
            out_specs=(pspec, ospec, sspec, P(), P()),
        ),
        donate_argnums=(0, 1, 2),
    )
    evaluate = jax.jit(shard_map(
        eval_step, mesh=mesh,
        in_specs=(pspec, sspec, P("dp"), P("dp")),
        out_specs=(P(), P()),
    ))
    return train, evaluate


def validate(evaluate, params, bn_stats, val_pool):
    """Full pass over the fixed val set → (mean loss, prec@1, prec@5)
    in percent (reference: main_amp.py ``validate``)."""
    tot = np.zeros(3)
    losses = []
    for images, labels in val_pool:
        loss, meters = evaluate(params, bn_stats, images, labels)
        losses.append(float(loss))
        tot += np.asarray(meters)
    c1, c5, n = tot
    return float(np.mean(losses)), 100.0 * c1 / n, 100.0 * c5 / n


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=32,
                    help="per-device batch")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--steps-per-epoch", type=int, default=20)
    ap.add_argument("--eval-steps", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="save per-epoch checkpoints + best.ckpt here")
    ap.add_argument("--resume", action="store_true",
                    help="restore the latest checkpoint from "
                         "--checkpoint-dir before training")
    ap.add_argument("--evaluate", action="store_true",
                    help="validation only (with --resume to score a "
                         "saved model)")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="append structured step metrics + checkpoint "
                         "events here")
    args = ap.parse_args(argv)

    mesh = parallel_state.initialize_model_parallel()
    dp = mesh.shape["dp"]
    model = ResNet(ResNetConfig(depth=args.depth,
                                num_classes=args.num_classes))
    opt = FusedSGD(lr=args.lr, momentum=0.9, weight_decay=1e-4,
                   master_weights=True)

    params, bn_stats = model.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    start_epoch, best_prec1 = 0, 0.0

    if args.resume:
        if not args.checkpoint_dir:
            raise SystemExit("--resume needs --checkpoint-dir")
        last = checkpoint.latest_step(args.checkpoint_dir)
        if last is None:
            print(f"no checkpoint under {args.checkpoint_dir}; "
                  "starting fresh")
        else:
            target = {"params": params, "opt_state": opt_state,
                      "bn_stats": bn_stats,
                      "epoch": np.int64(0), "best_prec1": np.float64(0.0)}
            state = checkpoint.restore_step(
                args.checkpoint_dir, target=target, step=last
            )
            params, opt_state, bn_stats = (
                state["params"], state["opt_state"], state["bn_stats"]
            )
            start_epoch = int(state["epoch"]) + 1
            best_prec1 = float(state["best_prec1"])
            print(f"resumed epoch {int(state['epoch'])} "
                  f"(best prec@1 {best_prec1:.2f}) from "
                  f"{args.checkpoint_dir}")

    train, evaluate = build_steps(
        model, opt, args.num_classes, mesh, params, opt_state, bn_stats
    )
    global_batch = args.batch_size * dp
    # small cycled pool for training, fixed set for validation (host
    # RNG stays out of the timed loop; val scores are comparable)
    train_pool = synthetic_pool(
        0, min(args.steps_per_epoch, 8), global_batch, args.image_size,
        args.num_classes,
    )
    val_pool = synthetic_pool(
        1, args.eval_steps, global_batch, args.image_size,
        args.num_classes,
    )

    if args.evaluate:
        loss, p1, p5 = validate(evaluate, params, bn_stats, val_pool)
        print(f"eval: loss {loss:.3f}  prec@1 {p1:.2f}  prec@5 {p5:.2f}")
        return {"prec1": p1, "prec5": p5}

    # telemetry: per-step loss/meters stay device futures; ONE batched
    # device_get resolves the whole epoch (the old loop synced twice
    # per step: float(loss) + np.asarray(meters)).  ms/step excludes
    # the first step of each epoch (only epoch 0's includes a compile,
    # but the exclusion is uniform — the same timing contract as the
    # gpt/bert/t5 trainers)
    stats = StepStats(tokens_per_step=global_batch, unit="img")
    # close() (the with-exit) deregisters the logger from the event
    # bus, so an exception mid-epoch cannot leak the sink or the fd
    with MetricsLogger(jsonl_path=args.metrics_jsonl, console=False,
                       flush_every=max(args.steps_per_epoch, 1),
                       run="imagenet_amp").attach_events() as tlm:
        return _train_epochs(
            args, tlm, stats, train, evaluate, train_pool, val_pool,
            params, opt_state, bn_stats, start_epoch, best_prec1,
            global_batch)


def _train_epochs(args, tlm, stats, train, evaluate, train_pool,
                  val_pool, params, opt_state, bn_stats, start_epoch,
                  best_prec1, global_batch):
    for epoch in range(start_epoch, args.epochs):
        held = []  # (loss, meters) device pairs, resolved at epoch end
        for i in range(args.steps_per_epoch):
            images, labels = train_pool[i % len(train_pool)]
            params, opt_state, bn_stats, loss, meters = train(
                params, opt_state, bn_stats, images, labels
            )
            held.append((loss, meters))
            if i == 0:
                stats.begin((loss, meters))  # blocks once per epoch
            else:
                stats.tick()
            tlm.log_scalars(epoch * args.steps_per_epoch + i, loss=loss)
        summary = stats.summary(held[-1] if held else None)
        resolved = jax.device_get(held)  # one transfer for the epoch
        losses = [float(l) for l, _ in resolved]
        tot = np.sum([np.asarray(m) for _, m in resolved], axis=0) \
            if resolved else np.zeros(3)
        ips = summary.get("tokens_per_sec", float("nan"))
        c1, c5, n = tot
        print(f"epoch {epoch}: loss {np.mean(losses):.3f}  "
              f"prec@1 {100 * c1 / n:.2f}  prec@5 {100 * c5 / n:.2f}  "
              f"{ips:,.1f} img/s ({ips / max(jax.device_count(), 1):,.1f}"
              f"/chip)")

        val_loss, p1, p5 = validate(evaluate, params, bn_stats, val_pool)
        is_best = p1 > best_prec1
        best_prec1 = max(best_prec1, p1)
        print(f"  val: loss {val_loss:.3f}  prec@1 {p1:.2f}  "
              f"prec@5 {p5:.2f}  best {best_prec1:.2f}"
              f"{'  *' if is_best else ''}")

        if args.checkpoint_dir:
            state = {"params": params, "opt_state": opt_state,
                     "bn_stats": bn_stats, "epoch": epoch,
                     "best_prec1": best_prec1}
            path = checkpoint.save_step(args.checkpoint_dir, epoch, state)
            if is_best:
                checkpoint.save(
                    os.path.join(args.checkpoint_dir, "best.ckpt"), state
                )
            print(f"  saved {path}" + ("  (best)" if is_best else ""))

    return {"params": params, "opt_state": opt_state,
            "bn_stats": bn_stats, "best_prec1": best_prec1}


if __name__ == "__main__":
    main()

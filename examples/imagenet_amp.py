"""ImageNet-style ResNet training — the flagship throughput example
(reference: examples/imagenet/main_amp.py: RN50 + amp O2 + apex DDP +
SyncBN).  Synthetic data by default so it runs without a dataset; plug a
real input pipeline into `batches()` for actual training.

    python examples/imagenet_amp.py --depth 50 --batch-size 32 --steps 20
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models.resnet import ResNet, ResNetConfig
from apex_tpu.optimizers import FusedSGD
from apex_tpu.transformer import parallel_state


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=32,
                    help="per-device batch")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--num-classes", type=int, default=1000)
    args = ap.parse_args()

    mesh = parallel_state.initialize_model_parallel()
    dp = mesh.shape["dp"]
    model = ResNet(ResNetConfig(depth=args.depth,
                                num_classes=args.num_classes))
    # O2 analog: bf16 compute (model casts internally), fp32 masters in
    # the optimizer, BN in fp32 (sync over dp)
    opt = FusedSGD(lr=args.lr, momentum=0.9, weight_decay=1e-4,
                   master_weights=True)

    params, bn_stats = model.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)

    def train_step(params, opt_state, bn_stats, images, labels):
        def loss_fn(p, stats):
            logits, new_stats = model.apply(p, stats, images, training=True)
            one_hot = jax.nn.one_hot(labels, args.num_classes)
            loss = -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1)
            )
            return loss, new_stats

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params, bn_stats)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        # BN running stats: average across dp like the reference's SyncBN
        new_stats = jax.tree.map(
            lambda s: jax.lax.pmean(s, "dp"), new_stats
        )
        new_params, new_opt = opt.step(opt_state, grads, params)
        return new_params, new_opt, new_stats, jax.lax.pmean(loss, "dp")

    to_spec = lambda tree: jax.tree.map(lambda _: P(), tree)
    step = jax.jit(
        jax.shard_map(
            train_step, mesh=mesh,
            in_specs=(to_spec(params), to_spec(opt_state), to_spec(bn_stats),
                      P("dp"), P("dp")),
            out_specs=(to_spec(params), to_spec(opt_state),
                       to_spec(bn_stats), P()),
        ),
        donate_argnums=(0, 1, 2),
    )

    rng = np.random.default_rng(0)
    global_batch = args.batch_size * dp
    images = jnp.asarray(rng.normal(
        size=(global_batch, args.image_size, args.image_size, 3)
    ).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, args.num_classes, (global_batch,)))

    # warmup/compile
    params, opt_state, bn_stats, loss = step(
        params, opt_state, bn_stats, images, labels
    )
    float(loss)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt_state, bn_stats, loss = step(
            params, opt_state, bn_stats, images, labels
        )
    lv = float(loss)
    dt = time.perf_counter() - t0
    ips = global_batch * args.steps / dt
    print(f"loss {lv:.3f}  {dt / args.steps * 1e3:.1f} ms/step  "
          f"{ips:,.1f} images/sec ({ips / max(jax.device_count(), 1):,.1f}"
          f"/chip)")


if __name__ == "__main__":
    main()

"""Encoder-decoder (T5-style) training across a pipeline split — the
example for `ModelType.encoder_and_decoder` (reference capability:
pipeline_model_parallel_split_rank in apex/transformer/parallel_state.py
+ schedules/common.py; the reference ships no runnable enc-dec example,
this framework does).

Stages [0, split) run the encoder, [split, pp) the decoder; the
cross-attention memory rides the ppermute ring with its microbatch
(apex_tpu.transformer.pipeline_parallel.pipeline_encdec).

Runs anywhere: real TPU chips or virtual CPU devices
(XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).

    python examples/t5_pipeline.py
    # hierarchical dp with an int8-compressed DCN leg:
    python examples/t5_pipeline.py --dp-ici-size 2 --grad-compression int8
"""

import argparse

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu._compat import shard_map
from apex_tpu.models import T5Config, T5Model
from apex_tpu.optimizers import FusedAdam
from apex_tpu.telemetry.metrics import MetricsLogger, StepStats
from apex_tpu.telemetry.spans import phase
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.layers import state_specs_like

VOCAB = 128
STEPS = 60


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--log-every", type=int, default=10,
                    help="telemetry flush cadence: the loss resolves "
                         "every N steps (no per-step host sync)")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="append structured step metrics here")
    ap.add_argument("--dp-ici-size", type=int, default=None,
                    help="hierarchical data parallelism: replicas per "
                         "fast-interconnect group")
    ap.add_argument("--grad-compression", default="none",
                    choices=["none", "int8"],
                    help="int8-quantize the DCN leg of the hierarchical "
                         "gradient reduce (requires --dp-ici-size)")
    ap.add_argument("--compress-ici-legs", action="store_true",
                    help="ALSO int8-quantize the ICI RS/AG legs of "
                         "the hierarchical reduce (requires "
                         "--grad-compression int8)")
    ap.add_argument("--no-error-feedback", action="store_true")
    ap.add_argument("--zero3", "--param-shard", action="store_true",
                    dest="zero3",
                    help="full-parameter sharding (ZeRO-3/FSDP) over "
                         "the data axis, composed with the pipeline: "
                         "each pp stage keeps its local stack as a "
                         "1-D fp32 shard, gathered per bucket on use")
    ap.add_argument("--bucket-mb-zero3", type=float, default=None,
                    help="ZeRO-3 gather bucket size in MiB "
                         "(defaults to --bucket-mb)")
    ap.add_argument("--overlap-grad-sync", action="store_true",
                    help="bucket the hierarchical gradient reduce so "
                         "the scheduler can overlap the per-bucket "
                         "collectives (requires --dp-ici-size)")
    ap.add_argument("--bucket-mb", type=float, default=4.0,
                    help="bucket size in MiB for --overlap-grad-sync")
    args = ap.parse_args(argv)

    hier = args.dp_ici_size is not None
    if args.grad_compression != "none" and not hier:
        ap.error("--grad-compression requires --dp-ici-size")
    if args.overlap_grad_sync and not hier:
        ap.error("--overlap-grad-sync requires --dp-ici-size")
    if args.compress_ici_legs and args.grad_compression == "none":
        ap.error("--compress-ici-legs requires --grad-compression int8")
    bucket_bytes = int(args.bucket_mb * 1024 * 1024)
    comp = None
    if args.grad_compression != "none":
        from apex_tpu.ops.quantization import CompressionConfig

        comp = CompressionConfig(
            method=args.grad_compression,
            error_feedback=not args.no_error_feedback,
            ici_legs=args.compress_ici_legs,
        )

    n = jax.device_count()
    pp = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    if pp < 2:
        raise SystemExit("need >= 2 devices for a pipeline split "
                         "(set XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=8 JAX_PLATFORMS=cpu)")
    if hier and n // pp % args.dp_ici_size:
        raise SystemExit(f"data extent {n // pp} is not divisible by "
                         f"--dp-ici-size {args.dp_ici_size}")
    split = pp // 2
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp,
        pipeline_model_parallel_split_rank_=split,
        data_parallel_ici_size_=args.dp_ici_size,
    )
    data_axes = parallel_state.data_parallel_axis_names()
    dp = parallel_state.get_data_parallel_world_size()
    print(f"devices={n} pp={pp} (enc stages {split}, dec {pp - split}) dp={dp}")

    model = T5Model(T5Config(
        vocab_size=VOCAB,
        num_encoder_layers=split * 2,
        num_decoder_layers=(pp - split) * 2,
        hidden_size=64,
        num_attention_heads=4,
        max_position_embeddings=32,
        compute_dtype=jnp.float32,
        remat=False,
        attention_impl="xla",
    ))
    params = model.pipeline_params(model.init(jax.random.PRNGKey(0)))
    specs = model.pipeline_param_specs()
    # no --fused-opt-tail here: the tail packs REPLICATED param state,
    # and this trainer's params are always pp-stacked (the packed
    # buffers cannot be described by a PartitionSpec — see
    # docs/optimizers.md "Fused optimizer tail" scope note).  --zero3
    # composes fine: each (pp, tp) position runs its own data-axis
    # shard of its local stack (model_axes in every spec below)
    if args.zero3:
        from apex_tpu.contrib.optimizers import (
            DistributedFusedAdam,
            reestablish_replicated,
        )

        zb = args.bucket_mb_zero3
        opt = DistributedFusedAdam(
            lr=3e-3, param_specs=specs,
            axis_name=data_axes if hier else "dp",
            compression=comp, shard_params=True,
            bucket_bytes=int((args.bucket_mb if zb is None else zb)
                             * 1024 * 1024))
        opt.build_layout(params, mesh=mesh)
        shard_spec = opt.shard_spec(model_axes=("pp", "tp"))
        opt_specs = opt.state_specs(model_axes=("pp", "tp"))
        init_shards = jax.jit(shard_map(
            opt.init_shards, mesh=mesh, in_specs=(specs,),
            out_specs=shard_spec))
    else:
        opt = FusedAdam(lr=3e-3)
        opt_state = opt.init(params)
        opt_specs = state_specs_like(specs, opt_state)

    # error-feedback residual state for the compressed reduce
    # (per-BUCKET residuals when the reduce is bucketed; under --zero3
    # the residuals ride the optimizer state instead)
    use_comm = (comp is not None and comp.error_feedback
                and not args.zero3)
    if use_comm:
        from apex_tpu.parallel.distributed import (
            comm_state_specs,
            init_comm_state,
        )

        if args.overlap_grad_sync:
            from apex_tpu.parallel import GradientBuckets

            plan = GradientBuckets.for_tree(
                params, bucket_bytes, param_specs=specs, mesh=mesh)
            comm_state = init_comm_state(
                params, data_axes, comp, mesh=mesh, param_specs=specs,
                buckets=plan)
            comm_specs = comm_state_specs(comm_state, data_axes,
                                          buckets=plan)
        else:
            comm_state = init_comm_state(
                params, data_axes, comp, mesh=mesh, param_specs=specs)
            comm_specs = comm_state_specs(comm_state, data_axes,
                                          param_specs=specs)
    else:
        comm_state, comm_specs = {}, {}

    def train_step(params, opt_state, comm, enc, dec, tgt):
        # flat dp: no explicit grad-pmean needed — pipeline_loss pmeans
        # the loss over "dp" internally, so differentiating it inserts
        # the dp grad reduction automatically (shard_map's replication
        # check on out_specs would reject divergent updates otherwise).
        # Hierarchical dp: the internal pmean rides the size-1 dummy
        # axis, so the data mean over (dcn, ici) happens explicitly —
        # RS(ici) -> AR(dcn, int8 when compressed) -> AG(ici)
        # --zero3: gather the local stack's weights per bucket first,
        # re-establishing the replicated typing over pp/tp the
        # pipeline collectives expect
        if args.zero3:
            weights, opt_state = opt.gather_params(params, opt_state)
            weights = reestablish_replicated(weights, specs)
        else:
            weights = params
        with phase("fwd_bwd"):
            loss, grads = jax.value_and_grad(
                lambda p: model.pipeline_loss(p, enc, dec, tgt,
                                              num_microbatches=2)
            )(weights)
        if args.zero3:
            if hier:
                loss = jax.lax.pmean(loss, data_axes)
        elif hier:
            from apex_tpu.parallel import all_reduce_gradients

            loss = jax.lax.pmean(loss, data_axes)
            if use_comm:
                grads, comm = all_reduce_gradients(
                    grads, axis_name=data_axes, compression=comp,
                    comm_state=comm,
                    overlap_grad_sync=args.overlap_grad_sync,
                    bucket_bytes=bucket_bytes)
            else:
                grads = all_reduce_gradients(
                    grads, axis_name=data_axes, compression=comp,
                    overlap_grad_sync=args.overlap_grad_sync,
                    bucket_bytes=bucket_bytes)
        with phase("optimizer"):
            params, opt_state = opt.step(opt_state, grads, params)
        return params, opt_state, comm, loss

    data_spec = P(data_axes if hier else "dp")
    store_spec = shard_spec if args.zero3 else specs
    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(store_spec, opt_specs, comm_specs,
                  data_spec, data_spec, data_spec),
        out_specs=(store_spec, opt_specs, comm_specs, P()),
    ))
    place = lambda tree, sp: jax.device_put(
        tree, jax.tree.map(lambda s: NamedSharding(mesh, s), sp,
                           is_leaf=lambda x: isinstance(x, P)))

    # toy copy task: decode the reversed source sequence
    ks = jax.random.split(jax.random.PRNGKey(1), 2)
    enc_tokens = jax.random.randint(ks[0], (4 * dp, 16), 0, VOCAB)
    dec_tokens = jnp.flip(enc_tokens, axis=1)
    targets = jnp.roll(dec_tokens, -1, axis=1)

    if args.zero3:
        p = init_shards(place(params, specs))
        s = jax.jit(shard_map(
            opt.init, mesh=mesh, in_specs=(shard_spec,),
            out_specs=opt_specs))(p)
        jax.block_until_ready(p)
        del params  # the shards are the storage — drop the full tree
    else:
        p, s = place(params, specs), place(opt_state, opt_specs)
    cst = place(comm_state, comm_specs)
    # async harvesting: the loss stays a device future between flushes
    # — no per-step host sync; ms/step excludes the first-step compile
    # (stats.begin blocks on step 0, the clock starts after), the same
    # timing contract as the other example trainers
    stats = StepStats(tokens_per_step=dec_tokens.shape[0]
                      * dec_tokens.shape[1])
    with MetricsLogger(jsonl_path=args.metrics_jsonl,
                       flush_every=args.log_every, stats=stats,
                       run="t5_pipeline") as tlm:
        loss = None
        for i in range(STEPS):
            p, s, cst, loss = step(p, s, cst, enc_tokens, dec_tokens,
                                   targets)
            if i == 0:
                stats.begin(loss)
            else:
                stats.tick()
            tlm.log_scalars(i, loss=loss)
        summary = stats.summary(loss)
    if summary.get("timed_steps"):
        print(f"{summary['ms_per_step']:.1f} ms/step  "
              f"{summary['tokens_per_sec']:,.0f} dec tokens/s")
    print("done")


if __name__ == "__main__":
    main()

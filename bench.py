"""Benchmark: flagship GPT training-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "gpt_tp1_tokens_per_sec", "value": N, "unit": "tokens/s",
   "vs_baseline": R}

``vs_baseline`` is the speedup of the framework's fast path (bf16 compute
+ flash attention + fused master-weight Adam — the amp-O5 analog) over an
O0-analog baseline measured in the same run (fp32 compute, XLA attention,
same optimizer math).  The reference publishes no numeric baselines
(BASELINE.md), so the baseline is measured, not copied.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.layers import state_specs_like

BATCH = 8
SEQ = 1024
WARMUP = 2
STEPS = 10


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_step(fast: bool):
    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel()
    cfg = GPTConfig(
        vocab_size=32768,
        num_layers=12,
        hidden_size=1024,
        num_attention_heads=8,  # head_dim 128 = one MXU lane tile
        max_position_embeddings=SEQ,
        compute_dtype=jnp.bfloat16 if fast else jnp.float32,
        attention_impl=None if fast else "xla",
        remat=True,
    )
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    specs = model.param_specs()
    opt = FusedAdam(lr=1e-4, master_weights=fast)
    opt_state = opt.init(params)
    opt_specs = state_specs_like(specs, opt_state)

    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens, targets)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        new_params, new_opt = opt.step(opt_state, grads, params)
        return new_params, new_opt, loss

    step = jax.jit(
        jax.shard_map(
            train_step,
            mesh=mesh,
            in_specs=(specs, opt_specs, P("dp"), P("dp")),
            out_specs=(specs, opt_specs, P()),
        ),
        donate_argnums=(0, 1),
    )
    place = lambda tree, sp: jax.device_put(
        tree,
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), sp,
            is_leaf=lambda x: isinstance(x, P),
        ),
    )
    if fast:
        # bf16 model params, fp32 masters live in the optimizer state
        params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    return place(params, specs), place(opt_state, opt_specs), step


def run(fast: bool) -> float:
    params, opt_state, step = build_step(fast)
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (BATCH, SEQ), 0, 32768)
    targets = jnp.roll(tokens, -1, axis=1)
    for _ in range(WARMUP):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    # host readback, not block_until_ready: the axon tunnel backend's
    # block_until_ready returns before device execution completes, and the
    # data dependency through `loss` is what forces the whole step chain
    float(loss)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert jnp.isfinite(final_loss), "non-finite loss in benchmark"
    tps = BATCH * SEQ * STEPS / dt
    log(f"{'fast' if fast else 'base'}: {dt/STEPS*1e3:.1f} ms/step, "
        f"{tps:,.0f} tokens/s, loss {final_loss:.3f}")
    return tps


def main():
    log(f"devices: {jax.devices()}")
    base = run(fast=False)
    fast = run(fast=True)
    print(
        json.dumps(
            {
                "metric": "gpt_tp1_tokens_per_sec",
                "value": round(fast, 1),
                "unit": "tokens/s",
                "vs_baseline": round(fast / base, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

"""Benchmark: flagship GPT training-step throughput (+ MFU) on one chip.

Prints ONE JSON line on stdout:
  {"metric": "gpt_tp1_tokens_per_sec", "value": N, "unit": "tokens/s",
   "vs_baseline": R, ...extra diagnostic fields...}

``vs_baseline`` is the speedup of the framework's fast path (bf16 compute
+ flash attention + fused master-weight Adam — the amp-O5 analog) over an
O0-analog baseline measured in the same run (fp32 compute, XLA attention,
same optimizer math).  The reference publishes no numeric baselines
(BASELINE.md), so the baseline is measured, not copied.

Resilience (the round-1 bench died at backend init with no retry and no
diagnostics): this file is an orchestrator that runs the measurement in
bounded subprocesses — a TPU-tunnel hang cannot eat the whole bench — and
retries backend init with backoff.  If the TPU stays unreachable it falls
back to a CPU measurement (clearly marked) and ALWAYS emits a valid JSON
line, never a bare traceback.

Extra BASELINE.md targets (RN50-style images/sec, FusedLAMB step time vs
an unfused per-tensor LAMB with identical math) are also measured —
platform-marked, scaled down on the CPU fallback — and written to
BENCH_EXTRA.json + stderr, keeping stdout a single line.
"""

import json
import os
import signal
import subprocess
import sys
import time

# Flagship GPT measurement config (the TPU path of child_gpt);
# tools/profile_r05.py decomposes the SAME program — one definition so
# the decomposition's headline cannot drift from the bench headline
FLAGSHIP = dict(vocab_size=32768, num_layers=12, hidden_size=1024,
                num_attention_heads=8, seq=1024, batch=8)

# one LONG probe window, not many short ones: a free chip grants in
# ~20 s so the cap never binds in the good case, while during a pool
# wedge a queued claim must WAIT (r5 watcher data: claim requests are
# told "no" only after ~25 min) — short probes always die mid-queue and
# every SIGTERM'd teardown is itself a re-wedge risk.  1440 s keeps
# MEASURE_RESERVE intact within the default 3000 s gate budget.
PROBE_TIMEOUT = int(os.environ.get("APEX_BENCH_PROBE_TIMEOUT", "1440"))
CHILD_TIMEOUT = int(os.environ.get("APEX_BENCH_CHILD_TIMEOUT", "1200"))
TOTAL_BUDGET = int(os.environ.get("APEX_BENCH_TOTAL_BUDGET", "3000"))
# Time reserved after a successful probe for the actual measurement
# (TPU gpt child + a slice for extras); the probe loop may consume
# everything before this point.  The axon chip-claim wedge can last
# >1h, so probing briefly and giving up (the round-3 failure: 3x180s)
# wastes the whole gate — instead probe with backoff until only the
# reserve is left.
MEASURE_RESERVE = int(os.environ.get("APEX_BENCH_MEASURE_RESERVE", "1500"))
# The probe LOOP's own wall cap, separate from the per-attempt window:
# BENCH_r05 burned ~1500 s (everything down to the reserve) probing an
# unreachable TPU before the CPU fallback even started.  At least one
# attempt always runs unless the budget is 0 (= skip probing entirely).
PROBE_BUDGET = int(os.environ.get("APEX_TPU_BENCH_PROBE_BUDGET", "600"))
# How long a cached probe failure from the SAME BOOT suppresses the
# probe (BENCH_WATCH.json "probe_failure" record): a wedged chip claim
# does not heal in minutes, so back-to-back gate runs should not each
# re-pay the probe budget.  0 disables the cache check (tpu_watch sets
# this for its post-contact full-bench run, where the chip is known
# reachable).
PROBE_CACHE_S = int(os.environ.get("APEX_TPU_BENCH_PROBE_CACHE_S", "10800"))
# Persisted by tools/tpu_watch.py on capture; this bench also parks its
# probe-failure cache here (merged, so a capture record is never lost)
BENCH_WATCH_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_WATCH.json"
)
# Persisted record of the last successful TPU-captured bench, so a
# flaky tunnel at gate time cannot erase hardware evidence: the CPU
# fallback output carries this forward as `last_tpu_result`.
LAST_TPU_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "LAST_TPU_BENCH.json"
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _git_sha():
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip()[:12]
    except Exception:
        return "unknown"


def _save_last_tpu(result, extras=None):
    try:
        rec = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
               "git_sha": _git_sha(), "result": result}
        if extras is not None:
            rec["extras"] = extras
        elif os.path.exists(LAST_TPU_PATH):
            # keep previously captured extras if this run didn't get any
            try:
                with open(LAST_TPU_PATH) as f:
                    old = json.load(f)
                if "extras" in old:
                    rec["extras"] = old["extras"]
                    rec["extras_captured_at"] = old.get(
                        "extras_captured_at", old.get("captured_at"))
            except Exception:
                pass
        with open(LAST_TPU_PATH, "w") as f:
            json.dump(rec, f, indent=1)
    except OSError as e:
        log(f"last-tpu record write failed: {e}")


def _load_last_tpu():
    try:
        with open(LAST_TPU_PATH) as f:
            return json.load(f)
    except Exception:
        return None


def _boot_id():
    """Kernel boot id — the cache key that makes a probe-failure record
    die with the machine: a reboot resets the axon claim state, so a
    pre-reboot failure must not suppress post-reboot probes."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip() or None
    except OSError:
        return None


def _load_watch():
    try:
        with open(BENCH_WATCH_PATH) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except Exception:
        return {}


def _cached_probe_failure():
    """A same-boot, recent probe failure record (or None)."""
    if PROBE_CACHE_S <= 0:
        return None
    rec = _load_watch().get("probe_failure")
    if not isinstance(rec, dict):
        return None
    boot = _boot_id()
    if boot is None or rec.get("boot_id") != boot:
        return None
    age = time.time() - rec.get("at", 0)
    if not (0 <= age <= PROBE_CACHE_S):
        return None
    return rec


def _set_probe_failure(rec):
    """Merge (rec != None) or clear (rec == None) the probe-failure
    cache without disturbing tpu_watch's capture record."""
    watch = _load_watch()
    if rec is None and "probe_failure" not in watch:
        return
    if rec is None:
        watch.pop("probe_failure", None)
    else:
        watch["probe_failure"] = rec
    # tmp + rename: this file also holds tpu_watch's captured hardware
    # evidence, which a SIGTERM mid-rewrite must not be able to destroy
    tmp = BENCH_WATCH_PATH + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(watch, f, indent=1)
        os.replace(tmp, BENCH_WATCH_PATH)
    except OSError as e:
        log(f"probe-failure cache write failed: {e}")


# --------------------------------------------------------------------- child
def _install_sigterm_exit():
    """Let a child exit cleanly on SIGTERM so the JAX client tears down
    and releases the chip claim (a hard kill wedges the axon pool's
    single-chip grant for >1h — observed round 3)."""
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))


def _pin_cpu():
    import jax

    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")


def _peak_flops(device):
    """Per-chip peak bf16 FLOP/s — the telemetry table
    (apex_tpu.telemetry.metrics.device_peak_flops), so the bench MFU
    and the live StepStats MFU share one denominator."""
    from apex_tpu.telemetry.metrics import device_peak_flops

    return device_peak_flops(device)


def child_probe():
    import jax

    d = jax.devices()[0]
    print(json.dumps({
        "platform": d.platform,
        "device_kind": getattr(d, "device_kind", ""),
        "n": len(jax.devices()),
    }))


def child_gpt(platform: str):
    if platform == "cpu":
        _pin_cpu()
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.tensor_parallel.layers import state_specs_like

    on_tpu = platform != "cpu"
    # CPU fallback uses a small config so the bench finishes on a 1-core
    # host; the TPU config is the real measurement
    cfg_common = dict(
        vocab_size=FLAGSHIP["vocab_size"] if on_tpu else 4096,
        num_layers=FLAGSHIP["num_layers"] if on_tpu else 2,
        hidden_size=FLAGSHIP["hidden_size"] if on_tpu else 256,
        num_attention_heads=(FLAGSHIP["num_attention_heads"]
                             if on_tpu else 4),
    )
    BATCH = FLAGSHIP["batch"] if on_tpu else 2
    # MFU is batch-sensitive: the fast path sweeps these and keeps the
    # best (HBM permitting — the sweep ends quietly at the first OOM),
    # the baseline uses BATCH for comparability
    FAST_BATCHES = (8, 16, 32, 64) if on_tpu else (2,)
    SEQ = FLAGSHIP["seq"] if on_tpu else 256
    WARMUP = 2
    STEPS = 10 if on_tpu else 4

    def build_step(fast: bool, **cfg_over):
        if parallel_state.model_parallel_is_initialized():
            parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        cfg = GPTConfig(
            max_position_embeddings=SEQ,
            compute_dtype=jnp.bfloat16 if fast else jnp.float32,
            attention_impl=(None if on_tpu else "xla") if fast else "xla",
            **{**cfg_common, "remat": True, **cfg_over},
        )
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        specs = model.param_specs()
        opt = FusedAdam(lr=1e-4, master_weights=fast)
        opt_state = opt.init(params)
        opt_specs = state_specs_like(specs, opt_state)

        def train_step(params, opt_state, tokens, targets):
            loss, grads = jax.value_and_grad(model.loss)(
                params, tokens, targets
            )
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            new_params, new_opt = opt.step(opt_state, grads, params)
            return new_params, new_opt, loss

        step = jax.jit(
            jax.shard_map(
                train_step,
                mesh=mesh,
                in_specs=(specs, opt_specs, P("dp"), P("dp")),
                out_specs=(specs, opt_specs, P()),
            ),
            donate_argnums=(0, 1),
        )
        place = lambda tree, sp: jax.device_put(
            tree,
            jax.tree.map(
                lambda s: NamedSharding(mesh, s), sp,
                is_leaf=lambda x: isinstance(x, P),
            ),
        )
        n_params = sum(x.size for x in jax.tree.leaves(params))
        if fast:
            # bf16 model params, fp32 masters live in the optimizer state
            params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
        return place(params, specs), place(opt_state, opt_specs), step, n_params

    def run(fast: bool, batch: int, **cfg_over):
        params, opt_state, step, n_params = build_step(fast, **cfg_over)
        key = jax.random.PRNGKey(1)
        tokens = jax.random.randint(
            key, (batch, SEQ), 0, cfg_common["vocab_size"]
        )
        targets = jnp.roll(tokens, -1, axis=1)
        for _ in range(WARMUP):
            params, opt_state, loss = step(params, opt_state, tokens, targets)
        # host readback, not block_until_ready: the axon tunnel backend's
        # block_until_ready returns before device execution completes; the
        # data dependency through `loss` forces the whole step chain
        float(loss)
        t0 = time.perf_counter()
        for _ in range(STEPS):
            params, opt_state, loss = step(params, opt_state, tokens, targets)
        final_loss = float(loss)
        dt = time.perf_counter() - t0
        assert jnp.isfinite(final_loss), "non-finite loss in benchmark"
        tps = batch * SEQ * STEPS / dt
        log(f"{'fast' if fast else 'base'} b={batch}: "
            f"{dt/STEPS*1e3:.1f} ms/step, {tps:,.0f} tokens/s, "
            f"loss {final_loss:.3f}")
        return tps, n_params

    log(f"devices: {jax.devices()}")
    base, _ = run(fast=False, batch=BATCH)
    fast, best_batch, n_params = 0.0, BATCH, 0
    fast_matched = None  # fast-path tokens/s at the baseline's batch
    last_err = None
    for b in FAST_BATCHES:
        try:
            tps, n_params = run(fast=True, batch=b)
        except AssertionError:
            raise  # non-finite loss is a correctness failure, never OOM
        except Exception as e:
            msg = str(e)
            oom = any(t in msg.upper() for t in
                      ("RESOURCE_EXHAUSTED", "OUT OF MEMORY", "OOM",
                       "ALLOCAT"))
            if not oom or fast == 0.0:
                raise  # only HBM exhaustion ends the sweep quietly
            last_err = e
            log(f"fast b={b} OOM ({msg[:120]}); keeping best so far")
            break
        if b == BATCH:
            fast_matched = tps
        if tps > fast:
            fast, best_batch = tps, b
    if fast == 0.0:
        raise RuntimeError("fast path failed at every batch") from last_err

    # in-process A/B of the r3/r4 perf levers (PROFILE_r03.md gap
    # decomposition): same process because chip-state drift between
    # processes is +-4% on this tunnel backend.  Each entry is
    # headline/variant tokens-per-sec, so >1 means the lever helps.
    ab = {}
    if on_tpu:
        # the default is fused_ce=None (auto by logits size, PROFILE_r05)
        # — the headline already runs whatever auto picks at best_batch,
        # so the informative variant is the FORCED OPPOSITE of that
        # choice.  New key name (fused_ce_auto_speedup) because the old
        # fused_ce_speedup trended the inverse lever (forced-off vs a
        # forced-fused headline); > 1 means auto beat the opposite path.
        # The prediction uses the dispatcher's own exported rule on the
        # shard_map-LOCAL sizes (tokens/dp, vocab/tp) — global shapes
        # would mispredict on any multi-device mesh.
        from apex_tpu.transformer.tensor_parallel.cross_entropy import (
            fused_ce_auto,
        )

        try:
            mesh = parallel_state.get_mesh()
            dp, tp = mesh.shape["dp"], mesh.shape["tp"]
        except Exception:
            # headline already captured — a surprise here must degrade
            # to the single-chip arithmetic, not lose the whole child
            dp = tp = 1
        auto_fused = fused_ce_auto(
            best_batch // dp * SEQ, cfg_common["vocab_size"] // tp
        )
        for tag, over in (
            ("fused_ce_auto", {"fused_ce": not auto_fused}),
            ("remat", {"remat": False}),
        ):
            try:
                tps_var, _ = run(fast=True, batch=best_batch, **over)
                ab[f"{tag}_speedup"] = round(fast / tps_var, 3)
            except Exception as e:
                # includes a variant's non-finite-loss assert: after the
                # headline is captured, a broken VARIANT is a finding to
                # record — re-raising would discard the whole scarce
                # TPU session and fall back to CPU
                ab[f"{tag}_speedup"] = None
                ab[f"{tag}_error"] = str(e)[:200]
                log(f"ab {tag} variant failed: {str(e)[:160]}")

    # model FLOPs per token: 6*N (fwd+bwd matmuls) + 12*L*h*s attention
    # — the shared estimate (telemetry.metrics), one numerator for
    # bench MFU and the live StepStats MFU
    from apex_tpu.telemetry.metrics import transformer_flops_per_token

    flops_per_token = transformer_flops_per_token(
        n_params, cfg_common["num_layers"], cfg_common["hidden_size"], SEQ
    )
    peak = _peak_flops(jax.devices()[0]) if on_tpu else None
    mfu = round(fast * flops_per_token / peak, 4) if peak else None
    print(json.dumps({
        "metric": "gpt_tp1_tokens_per_sec",
        "value": round(fast, 1),
        "unit": "tokens/s",
        # matched-batch comparison isolates the fast-path changes (bf16 +
        # flash + fused masters); batch-size scaling is reported via
        # value@best_batch separately.  CPU fallback: null, not a
        # number — bf16 has no CPU matrix units, so a ratio measured
        # there would misrepresent TPU (the note carries the why)
        "vs_baseline": (round((fast_matched or fast) / base, 3)
                        if on_tpu else None),
        "platform": platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
        "mfu": mfu,
        "n_params": n_params,
        "batch": best_batch,
        "seq": SEQ,
        "steps": STEPS,
        "warmup": WARMUP,
        "ms_per_step": round(best_batch * SEQ / fast * 1e3, 2),
        **({"ab": ab} if ab else {}),
        **({} if on_tpu else {"note": (
            "cpu fallback (TPU unreachable): bf16 has no CPU matrix "
            "units, so vs_baseline is not representative of TPU"
        )}),
    }))


def child_extras(platform: str):
    """BASELINE.md extra targets: RN50-ish images/sec (bf16+SyncBN-off,
    O2-analog) and FusedLAMB vs unfused per-tensor LAMB step time on a
    BERT-large-shaped param set (scaled down on the CPU fallback)."""
    if platform == "cpu":
        _pin_cpu()
    import jax
    import jax.numpy as jnp

    on_tpu = platform != "cpu"
    out = {"platform": platform}

    def _emit_partial():
        # cumulative snapshot after each section: if a later section's
        # cold compile outlives the child budget, _run_child salvages
        # the last JSON line instead of losing the whole run (the r5
        # round-start extras child died exactly this way)
        print(json.dumps({**out, "partial": True}), flush=True)

    # ---- RN50 images/sec, amp-O2 analog (bf16 compute, fp32 masters)
    from apex_tpu.models.resnet import ResNet, ResNetConfig
    from apex_tpu.optimizers import FusedAdam

    batch = 64 if on_tpu else 4
    size = 224 if on_tpu else 32
    model = ResNet(ResNetConfig(
        depth=50 if on_tpu else 18,
        compute_dtype=jnp.bfloat16,
        sync_bn_axis=None,
    ))
    params, batch_stats = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-3, master_weights=True)
    opt_state = opt.init(params)
    images = jax.random.normal(
        jax.random.PRNGKey(1), (batch, size, size, 3), jnp.bfloat16
    )
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)

    @jax.jit
    def rn_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, new_stats = model.apply(
                p, batch_stats, images, training=True
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(
                jnp.take_along_axis(logp, labels[:, None], axis=1)
            ), new_stats

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        new_params, new_opt = opt.step(opt_state, grads, params)
        return new_params, new_stats, new_opt, loss

    p = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    for _ in range(2):
        p, batch_stats, opt_state, loss = rn_step(
            p, batch_stats, opt_state, images, labels
        )
    float(loss)
    steps = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(steps):
        p, batch_stats, opt_state, loss = rn_step(
            p, batch_stats, opt_state, images, labels
        )
    float(loss)
    dt = time.perf_counter() - t0
    out["rn50_images_per_sec"] = round(batch * steps / dt, 1)
    out["rn50_batch"] = batch
    out["rn50_depth"] = model.config.depth
    out["rn50_image_size"] = size
    # measurement spec, so regressions are reproducible (VERDICT r2 #9)
    out["rn50_spec"] = {
        "steps": steps, "warmup": 2, "compute_dtype": "bfloat16",
        "params_dtype": "bfloat16 + fp32 masters (O2-analog)",
        "optimizer": "FusedAdam(master_weights=True)",
    }
    log(f"rn50: {out['rn50_images_per_sec']} images/s (batch {batch})")
    _emit_partial()

    # ---- FusedLAMB (one jitted pytree step) vs unfused LAMB (same math,
    # one dispatch per tensor per stage — the pre-multi-tensor torch
    # optimizer pattern the reference's fused kernels beat),
    # BERT-large-shaped tensor list (~1 embed + 4 mats x L layers)
    from apex_tpu.optimizers import FusedLAMB

    h, L, vocab = (1024, 24, 30522) if on_tpu else (256, 4, 1024)
    key = jax.random.PRNGKey(3)
    params = {"embed": jax.random.normal(key, (vocab, h)) * 0.02}
    for i in range(L):
        params[f"l{i}"] = {
            "qkv": jax.random.normal(key, (h, 3 * h)) * 0.02,
            "proj": jax.random.normal(key, (h, h)) * 0.02,
            "fc1": jax.random.normal(key, (h, 4 * h)) * 0.02,
            "fc2": jax.random.normal(key, (4 * h, h)) * 0.02,
        }
    grads = jax.tree.map(lambda p: p * 1e-3, params)

    lamb = FusedLAMB(lr=1e-3, use_nvlamb=True)
    lamb_state = lamb.init(params)
    lamb_step = jax.jit(lambda s, g, p: lamb.step(s, g, p))

    # unfused reference: identical LAMB math, leaf at a time
    b1, b2, eps, wd, lr, max_norm = 0.9, 0.999, 1e-6, 0.01, 1e-3, 1.0

    @jax.jit
    def leaf_sqnorm(g):
        return jnp.sum(jnp.square(g.astype(jnp.float32)))

    @jax.jit
    def leaf_lamb(p, g, m, v, clip, step):
        g = g.astype(jnp.float32) * clip
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        upd = mhat / (jnp.sqrt(vhat) + eps) + wd * p
        pn = jnp.sqrt(jnp.sum(jnp.square(p)))
        un = jnp.sqrt(jnp.sum(jnp.square(upd)))
        trust = jnp.where((pn > 0) & (un > 0), pn / un, 1.0)
        return p - lr * trust * upd, m, v

    def unfused_step(state, grads, params):
        leaves_p, treedef = jax.tree.flatten(params)
        leaves_g = jax.tree.leaves(grads)
        # one dispatch per tensor for the norm, host-side combine — the
        # unfused pattern (reference computes this fused in one kernel)
        gnorm = float(
            jnp.sqrt(sum(float(leaf_sqnorm(g)) for g in leaves_g))
        )
        clip = min(1.0, max_norm / max(gnorm, 1e-12))
        step = state["step"] + 1
        new_p, new_m, new_v = [], [], []
        for p_, g_, m_, v_ in zip(
            leaves_p, leaves_g, state["m"], state["v"]
        ):
            p2, m2, v2 = leaf_lamb(p_, g_, m_, v_, clip, step)
            new_p.append(p2)
            new_m.append(m2)
            new_v.append(v2)
        return (
            {"step": step, "m": new_m, "v": new_v},
            jax.tree.unflatten(treedef, new_p),
        )

    zeros = [jnp.zeros_like(x, jnp.float32) for x in jax.tree.leaves(params)]
    unfused_state = {"step": 0, "m": list(zeros), "v": list(zeros)}

    def timeit(fn, *args, n=20):
        # full host readback, not block_until_ready: the axon tunnel
        # backend's block_until_ready returns before device execution
        # completes; device_get of the last call's outputs forces the
        # in-order dispatch queue to drain
        jax.device_get(fn(*args))
        t0 = time.perf_counter()
        for _ in range(n):
            outp = fn(*args)
        jax.device_get(outp)
        return (time.perf_counter() - t0) / n * 1e3

    out["fused_lamb_ms"] = round(
        timeit(lamb_step, lamb_state, grads, params), 3
    )
    out["unfused_lamb_ms"] = round(
        timeit(unfused_step, unfused_state, grads, params), 3
    )
    out["lamb_speedup"] = round(
        out["unfused_lamb_ms"] / out["fused_lamb_ms"], 2
    )
    out["lamb_spec"] = {
        "timeit_iters": 20, "warmup": 1, "dtype": "float32",
        "shape": f"BERT-large-ish h={h} L={L} vocab={vocab} "
                 f"({1 + 4 * L} tensors)",
        "use_nvlamb": True,
    }
    log(f"lamb fused {out['fused_lamb_ms']} ms vs unfused "
        f"{out['unfused_lamb_ms']} ms ({out['lamb_speedup']}x)")
    _emit_partial()

    # ---- DCGAN-style multi-model / multi-loss-scaler step (BASELINE.md:
    # 'DCGAN multi-model/multi-loss scaling, functional, 3 loss scalers')
    from apex_tpu import amp as apex_amp

    mp = apex_amp.initialize(opt_level="O1", num_losses=3)
    gb, zdim, img = (64, 64, 784) if on_tpu else (16, 16, 64)
    kG, kD, kz = jax.random.split(jax.random.PRNGKey(4), 3)
    G = {"w1": 0.1 * jax.random.normal(kG, (zdim, 256)),
         "w2": 0.1 * jax.random.normal(kG, (256, img))}
    D = {"w1": 0.1 * jax.random.normal(kD, (img, 256)),
         "w2": 0.1 * jax.random.normal(kD, (256, 1))}
    g_opt = FusedAdam(lr=2e-4)
    d_opt = FusedAdam(lr=2e-4)
    g_state, d_state = g_opt.init(G), d_opt.init(D)
    amp_state = mp.init()
    real = jax.random.normal(jax.random.PRNGKey(5), (gb, img))

    def gen(Gp, z):
        h_ = jnp.tanh(z @ Gp["w1"].astype(z.dtype))
        return jnp.tanh(h_ @ Gp["w2"].astype(h_.dtype))

    def disc(Dp, x_):
        h_ = jnp.tanh(x_ @ Dp["w1"].astype(x_.dtype))
        return h_ @ Dp["w2"].astype(h_.dtype)

    bce = lambda logit, y: jnp.mean(
        jnp.maximum(logit, 0) - logit * y
        + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    )

    @jax.jit
    def gan_step(G, D, g_state, d_state, amp_state, z, real):
        low = jnp.float16
        # D step: two separately-scaled losses (real, fake), like the
        # reference's errD_real/errD_fake with per-loss scalers
        def d_loss_real(Dp):
            l = bce(disc(Dp, real.astype(low)).astype(jnp.float32), 1.0)
            return mp.scale_loss(amp_state, l, loss_id=0), l

        def d_loss_fake(Dp):
            fake = gen(jax.tree.map(lambda w: w.astype(low), G),
                       z.astype(low))
            l = bce(disc(Dp, fake).astype(jnp.float32), 0.0)
            return mp.scale_loss(amp_state, l, loss_id=1), l

        gr, lr_ = jax.grad(d_loss_real, has_aux=True)(D)
        gr, f0, amp_state = mp.unscale_and_adjust(amp_state, gr, loss_id=0)
        gf, lf_ = jax.grad(d_loss_fake, has_aux=True)(D)
        gf, f1, amp_state = mp.unscale_and_adjust(amp_state, gf, loss_id=1)
        d_grads = jax.tree.map(lambda a, b: a + b, gr, gf)
        D, d_state = d_opt.step(d_state, d_grads, D,
                                grads_finite=f0 & f1)

        # G step: third scaler
        def g_loss(Gp):
            fake = gen(jax.tree.map(lambda w: w.astype(low), Gp),
                       z.astype(low))
            l = bce(disc(jax.tree.map(lambda w: w.astype(low), D),
                         fake).astype(jnp.float32), 1.0)
            return mp.scale_loss(amp_state, l, loss_id=2), l

        gg, lg_ = jax.grad(g_loss, has_aux=True)(G)
        gg, f2, amp_state = mp.unscale_and_adjust(amp_state, gg, loss_id=2)
        G, g_state = g_opt.step(g_state, gg, G, grads_finite=f2)
        return G, D, g_state, d_state, amp_state, lr_ + lf_, lg_

    z = jax.random.normal(kz, (gb, zdim))
    for _ in range(2):
        G, D, g_state, d_state, amp_state, dl, gl = gan_step(
            G, D, g_state, d_state, amp_state, z, real
        )
    jax.device_get((dl, gl))
    gan_steps = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(gan_steps):
        G, D, g_state, d_state, amp_state, dl, gl = gan_step(
            G, D, g_state, d_state, amp_state, z, real
        )
    dl, gl = jax.device_get((dl, gl))
    dt = time.perf_counter() - t0
    out["dcgan_multi_scaler"] = {
        "ms_per_step": round(dt / gan_steps * 1e3, 3),
        "d_loss": round(float(dl), 4),
        "g_loss": round(float(gl), 4),
        "finite": bool(jnp.isfinite(dl)) and bool(jnp.isfinite(gl)),
        "spec": {"steps": gan_steps, "warmup": 2, "batch": gb,
                 "opt_level": "O1 (fp16 + 3 dynamic per-loss scalers)"},
    }
    log(f"dcgan: {out['dcgan_multi_scaler']}")
    _emit_partial()

    # ---- long-sequence flash attention (streamed-K/V capability on the
    # record: the reference's fmha caps at seqlen 512, setup.py:405-415).
    # Guarded: a failure here (e.g. HBM exhaustion) must not discard the
    # extras already measured above (same policy as the GPT child's OOM
    # handling).
    try:
        _flash_long_seq(out, on_tpu, timeit)
    except Exception as e:  # pragma: no cover - depends on chip state
        out["flash_long_seq"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        log(f"flash long-seq skipped: {type(e).__name__}")
    _emit_partial()
    try:
        _t5_extra(out, on_tpu)
    except Exception as e:  # pragma: no cover - depends on chip state
        out["t5_encdec"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        log(f"t5 extra skipped: {type(e).__name__}")
    print(json.dumps(out))


def child_gradsync():
    """Grad-sync A/B row: ms/step of a 2-microbatch accumulate+reduce
    loop on the 8-virtual-device (dcn=2 x ici=4) hierarchical mesh,
    overlap on/off x compression on/off, against a no-collective
    compute baseline — ``exposed_comm_ms`` is the difference.  Always
    runs on virtual CPU devices (a single TPU chip has no dp axis to
    reduce over), so per the PR 3 convention ``vs_baseline`` is null:
    the structural win is tracked by OVERLAP_AUDIT/COMM_AUDIT, this
    row tracks that the code paths stay runnable and their relative
    cost across PRs."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _pin_cpu()
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel import hierarchical_data_parallel_mesh
    from apex_tpu.parallel.distributed import Reducer

    if hasattr(jax, "shard_map"):
        smap = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as _sm

        def smap(f, mesh=None, in_specs=None, out_specs=None):
            return _sm(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=False)

    mesh = hierarchical_data_parallel_mesh(ici_size=4)
    L, W, ROWS, K = 4, 128, 16, 2
    ks = jax.random.split(jax.random.PRNGKey(0), L + 1)
    params = {f"l{i}": {"w": 0.1 * jax.random.normal(ks[i], (W, W)),
                        "b": jnp.zeros((W,))} for i in range(L)}
    params["head"] = 0.1 * jax.random.normal(ks[L], (W, 2 * W))

    def loss(p, x):
        h = x
        for i in range(L):
            h = jnp.tanh(h @ p[f"l{i}"]["w"] + p[f"l{i}"]["b"])
        z = h @ p["head"]
        return jnp.sum(z * z) / z.size

    pspec = jax.tree.map(lambda _: P(), params)
    data = jax.random.normal(
        jax.random.PRNGKey(1), (K, ROWS * 8, W))

    def build(reducer):
        # every variant returns ONE pmean'd scalar computed from its
        # (reduced or local) grads — a data dependency that keeps the
        # collectives alive, with an out-spec every shard_map
        # replication checker accepts
        def gsum(tree):
            return sum(jnp.sum(g * g) for g in jax.tree.leaves(tree))

        def step(p, batch):
            if reducer is None:  # compute-only baseline
                g = None
                for k in range(K):
                    gk = jax.grad(loss)(p, batch[k])
                    g = gk if g is None else jax.tree.map(
                        lambda a, b_: a + b_, g, gk)
                return jax.lax.pmean(gsum(g), ("dcn", "ici"))
            acc = reducer.init(p)
            for k in range(K):
                acc = reducer.accumulate(
                    acc, jax.grad(loss)(p, batch[k]))
            grads, _ = reducer.reduce(acc)
            return jax.lax.pmean(gsum(grads), ("dcn", "ici"))

        return jax.jit(smap(
            step, mesh=mesh,
            in_specs=(pspec, P(None, ("dcn", "ici"))),
            out_specs=P(),
        ))

    def measure(fn, steps=10):
        float(fn(params, data))
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(params, data)
        float(out)
        return (time.perf_counter() - t0) / steps * 1e3

    compute_ms = measure(build(None))
    rows = []
    for overlap in (False, True):
        for comp in (None, "int8"):
            red = Reducer(axis_name=("dcn", "ici"),
                          overlap_grad_sync=overlap,
                          bucket_bytes=96 * 1024, compression=comp)
            ms = measure(build(red))
            rows.append({
                "overlap": overlap,
                "compression": comp or "none",
                "ms_per_step": round(ms, 3),
                "exposed_comm_ms": round(max(ms - compute_ms, 0.0), 3),
            })
            log(f"grad-sync overlap={overlap} comp={comp or 'none'}: "
                f"{ms:.2f} ms/step")
    print(json.dumps({
        "metric": "grad_sync_ms_per_step",
        "platform": "cpu-virtual",
        # no TPU measurement happened on this mesh: null, not a fake
        # ratio (PR 3 convention)
        "vs_baseline": None,
        "note": "8 virtual CPU devices (dcn=2 x ici=4): relative cost "
                "only — DCN wall-clock wins are proven structurally "
                "by OVERLAP_AUDIT/COMM_AUDIT",
        "compute_only_ms": round(compute_ms, 3),
        "spec": {"layers": L, "width": W, "rows_per_device": ROWS,
                 "num_micro": K, "bucket_kb": 96, "steps": 10,
                 "warmup": 1},
        "rows": rows,
    }))


def child_zero3():
    """ZeRO-3 A/B row: ms/step of the full-parameter-sharding train
    step (gather-on-use weights + reduce-scatter grads + sharded
    update) vs the replicated FusedAdam step at the flagship
    CPU-dryrun GPT shape on the 8-virtual-device dp mesh, plus the
    param-gather cost measured in isolation.  Always a CPU
    measurement, so per the PR 3 convention ``vs_baseline`` is null —
    the memory win is proven structurally by MEMORY_AUDIT (compiled
    per-device bytes) and the wire win by ZERO3_AUDIT; this row tracks
    that the sharded path stays runnable and its step-time tax across
    PRs."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    _pin_cpu()
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.tensor_parallel.layers import (
        state_specs_like,
    )
    from apex_tpu._compat import shard_map

    # the flagship CPU-dryrun shape (child_gpt's fallback config)
    VOCAB, LAYERS, HIDDEN, HEADS, SEQ, BATCH = 4096, 2, 256, 4, 256, 8
    WARMUP, STEPS = 2, 10
    BUCKET_KB = 256
    mesh = parallel_state.initialize_model_parallel()
    model = GPTModel(GPTConfig(
        vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
        num_attention_heads=HEADS, max_position_embeddings=SEQ,
        compute_dtype=jnp.float32, attention_impl="xla", remat=False,
    ))
    params = model.init(jax.random.PRNGKey(0))
    specs = model.param_specs()
    place = lambda tree, sp: jax.device_put(
        tree, jax.tree.map(lambda s: NamedSharding(mesh, s), sp,
                           is_leaf=lambda x: isinstance(x, P)))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (BATCH, SEQ),
                                0, VOCAB)
    targets = jnp.roll(tokens, -1, axis=1)

    def measure(fn, *args):
        for _ in range(WARMUP):
            out = fn(*args)
        jax.block_until_ready(jax.tree.leaves(out)[-1])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = fn(*args)
        jax.block_until_ready(jax.tree.leaves(out)[-1])
        return (time.perf_counter() - t0) / STEPS * 1e3

    # replicated baseline
    ropt = FusedAdam(lr=1e-4, master_weights=True)
    rstate = ropt.init(params)
    rspecs = state_specs_like(specs, rstate)

    def rep_step(p, s, tok, tgt):
        loss, grads = jax.value_and_grad(model.loss)(p, tok, tgt)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        p, s = ropt.step(s, grads, p)
        return p, s, loss

    rstep = jax.jit(shard_map(
        rep_step, mesh=mesh,
        in_specs=(specs, rspecs, P("dp"), P("dp")),
        out_specs=(specs, rspecs, P())))
    rep_ms = measure(rstep, place(params, specs),
                     place(rstate, rspecs), tokens, targets)

    # zero3: gather-on-use
    opt = DistributedFusedAdam(lr=1e-4, shard_params=True,
                               bucket_bytes=BUCKET_KB * 1024)
    opt.build_layout(params, mesh=mesh)
    sspec, stspecs = opt.shard_spec(), opt.state_specs()
    shards = jax.jit(shard_map(
        opt.init_shards, mesh=mesh, in_specs=(specs,),
        out_specs=sspec))(place(params, specs))
    state = jax.jit(shard_map(
        opt.init, mesh=mesh, in_specs=(sspec,),
        out_specs=stspecs))(shards)

    def z3_step(sh, s, tok, tgt):
        p, s = opt.gather_params(sh, s)
        loss, grads = jax.value_and_grad(model.loss)(p, tok, tgt)
        sh, s = opt.step(s, grads, sh)
        return sh, s, loss

    zstep = jax.jit(shard_map(
        z3_step, mesh=mesh,
        in_specs=(sspec, stspecs, P("dp"), P("dp")),
        out_specs=(sspec, stspecs, P())))
    z3_ms = measure(zstep, shards, state, tokens, targets)

    # the gather alone: what one full weight materialization costs
    def gather_only(sh):
        p, _ = opt.gather_params(sh)
        return sum(jnp.sum(l) for l in jax.tree.leaves(p))

    gfn = jax.jit(shard_map(
        gather_only, mesh=mesh, in_specs=(sspec,), out_specs=P()))
    gather_ms = measure(gfn, shards)

    n_params = sum(int(l.size) for l in jax.tree.leaves(params))
    log(f"zero3: replicated {rep_ms:.2f} ms/step, zero3 {z3_ms:.2f} "
        f"ms/step, param-gather alone {gather_ms:.2f} ms")
    print(json.dumps({
        "metric": "zero3_ms_per_step",
        "value": round(z3_ms, 3),
        "unit": "ms/step (8 virtual CPU devices)",
        # no TPU measurement happened on this mesh: null, not a fake
        # ratio (PR 3 convention)
        "vs_baseline": None,
        "platform": "cpu-virtual",
        "note": "relative cost only — the memory win is MEMORY_AUDIT's "
                "compiled bytes, the wire win ZERO3_AUDIT's; this row "
                "tracks the sharded step's runnable cost across PRs",
        "ms_per_step_replicated": round(rep_ms, 3),
        "ms_per_step_zero3": round(z3_ms, 3),
        "param_gather_ms": round(gather_ms, 3),
        "exposed_zero3_tax_ms": round(max(z3_ms - rep_ms, 0.0), 3),
        "spec": {"vocab": VOCAB, "layers": LAYERS, "hidden": HIDDEN,
                 "heads": HEADS, "seq": SEQ, "batch": BATCH,
                 "n_params": n_params, "bucket_kb": BUCKET_KB,
                 "steps": STEPS, "warmup": WARMUP},
    }))


def child_decode():
    """Decode-throughput rows: tokens/s/chip of the fused serving
    decode step (paged cache + fmha_decode + on-device sampling, the
    whole ``GPTModel.decode_step`` pipeline) at decode batch
    {1, 8, 64, 256} for fp32 / bf16 / int8-KV caches, the
    WEIGHT-WIDTH rows: weight {bf16, int8, int4} x KV {fp32, int8} at
    batch {1, 8, 64} with the step's weight-stream GB/s, plus one
    mixed prefill+decode row (a continuous-batching window that admits
    a prompt mid-stream), the MIXED-LOAD rows: TTFT p50/p95 and
    decode-stall time of long-prompt arrivals with chunked prefill on
    vs off vs on-with-shared-prefix (prefix-cache hits) at decode
    batch {8, 64, 256}, and the SPECULATIVE rows: n-gram
    draft-and-verify (k=4) vs the plain step at batch {1, 8, 64} on
    repetitive vs adversarial prompts — tokens/s plus
    accepted-tokens/step, plus the TENSOR-PARALLEL rows: the sharded
    decode step at tp {1, 2, 4} x weight {bf16, int8, int4} with
    per-chip pool bytes and weight-stream GB/s/chip.  Runs the
    flagship CPU-dryrun GPT shape on ONE device (tp rows shard over
    virtual devices) so "per chip" is honest; always a CPU measurement here, so
    per the PR 3 convention ``vs_baseline`` is null — the row tracks
    that the serving stack stays runnable and how the variants rank,
    not a TPU rate."""
    _pin_cpu()
    # the tensor-parallel rows below shard over up to 4 virtual
    # devices — force the host split BEFORE jax initialises
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving.kv_cache import (
        KVCacheConfig, PagedKVCache, init_pools,
    )
    from apex_tpu.serving.serve import init_carry
    from apex_tpu.transformer import parallel_state

    # the flagship CPU-dryrun shape (child_gpt's fallback config)
    VOCAB, LAYERS, HIDDEN, HEADS, SEQ = 4096, 2, 256, 4, 256
    PAGE, PROMPT, WARMUP, STEPS = 32, 64, 2, 10
    BATCHES = [1, 8, 64, 256]
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:1])
    model = GPTModel(GPTConfig(
        vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
        num_attention_heads=HEADS,
        # the mixed-load rows admit 520-token prompts (512-token
        # shared prefix + tail) whose cache rounds up to 17 pages
        max_position_embeddings=1024,
        compute_dtype=jnp.float32, attention_impl="xla", remat=False,
    ))
    params = model.init(jax.random.PRNGKey(0))

    # weight-pool block for the quantized-weight rows: HIDDEN=256 puts
    # the projection widths at {768, 256, 1024} — block 64 divides
    # every one AND keeps whole blocks per int4 nibble half
    WQ_BLOCK = 64

    def run_variant(kv_name, batch, weight=None, mesh=mesh,
                    wq_block=WQ_BLOCK):
        kv_dtype = jnp.int8 if kv_name == "int8" else None
        dtype = (jnp.float32 if kv_name == "float32"
                 else jnp.bfloat16)
        pages_per_seq = -(-(PROMPT + STEPS + WARMUP + 4) // PAGE)
        cfg = KVCacheConfig(
            num_layers=LAYERS, num_heads=HEADS,
            head_dim=HIDDEN // HEADS,
            num_pages=1 + batch * pages_per_seq, page_size=PAGE,
            max_seqs=batch, pages_per_seq=pages_per_seq,
            dtype=dtype, kv_dtype=kv_dtype,
        )
        fns = model.decode_fns(params, mesh, cfg,
                               max_prompt_len=PROMPT,
                               weight_dtype=weight,
                               weight_block=wq_block)
        cache = PagedKVCache(cfg)
        pools = init_pools(cfg)
        carry = init_carry(batch)
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (1, PROMPT), 0, VOCAB
        ).astype(jnp.int32)
        key = jax.random.PRNGKey(2)
        t_pref = None
        for slot in range(batch):
            cache.admit(slot, PROMPT + STEPS + WARMUP + 4)
            t0 = time.perf_counter()
            pools, first = fns.prefill(
                pools, toks, jnp.int32(PROMPT),
                jnp.asarray(cache.page_table[slot]), key)
            jax.block_until_ready(first)
            t_pref = time.perf_counter() - t0   # last = steady-state
            carry = {
                "tokens": carry["tokens"].at[slot].set(first),
                "lengths": carry["lengths"].at[slot].set(PROMPT),
                "steps_left": carry["steps_left"].at[slot].set(
                    STEPS + WARMUP + 2),
                "done": carry["done"].at[slot].set(False),
                "sample_keys": carry["sample_keys"],
            }
        pt = jnp.asarray(cache.page_table)
        for _ in range(WARMUP):
            pools, carry = fns.decode(pools, carry, pt)
        jax.block_until_ready(carry["tokens"])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            pools, carry = fns.decode(pools, carry, pt)
        jax.block_until_ready(carry["tokens"])
        ms = (time.perf_counter() - t0) / STEPS * 1e3
        return ms, batch / ms * 1e3, t_pref * 1e3, \
            int(fns.weight_stream_bytes)

    rows = {}
    mixed_src = None
    for kv_name in ("float32", "bfloat16", "int8"):
        per_batch = {}
        for batch in BATCHES:
            ms, tps, pref_ms, _ = run_variant(kv_name, batch)
            per_batch[str(batch)] = {
                "ms_per_step": round(ms, 3),
                "tokens_per_sec_per_chip": round(tps, 1),
            }
            if kv_name == "bfloat16" and batch == 8:
                mixed_src = (ms, pref_ms)
            log(f"decode {kv_name} b{batch}: {ms:.2f} ms/step, "
                f"{tps:,.0f} tokens/s/chip")
        rows[kv_name] = per_batch

    # ---- weight-width rows: the quantized weight pools (block-wise
    # int8, packed int4 — dequantized inside the matmul tiles) vs the
    # bf16 cast, each over fp32 and int8 KV caches at batch {1,8,64}.
    # weight_stream_gbs is the decode step's weight traffic (the whole
    # param pool per step) over the measured wall — the roofline the
    # tentpole moves; on CPU the step is compute-bound, so the
    # in-tile dequant arithmetic can RAISE ms/step while the weight
    # bytes shrink — the TPU capture reads the GB/s column, not the
    # CPU wall ratio.
    wq = {}
    for weight in ("bf16", "int8", "int4"):
        per_w = {}
        for kv_name in ("float32", "int8"):
            per_b = {}
            for batch in (1, 8, 64):
                ms, tps, _, wbytes = run_variant(
                    kv_name, batch, weight=weight)
                per_b[str(batch)] = {
                    "ms_per_step": round(ms, 3),
                    "tokens_per_sec_per_chip": round(tps, 1),
                    "weight_stream_gbs": round(
                        wbytes / ms * 1e3 / 1e9, 3),
                }
                log(f"decode w={weight} kv={kv_name} b{batch}: "
                    f"{ms:.2f} ms/step, {tps:,.0f} tokens/s/chip")
            per_w[f"kv_{kv_name}"] = per_b
        per_w["weight_pool_bytes"] = wbytes
        wq[weight] = per_w
    wq["note"] = (
        f"weight_block={WQ_BLOCK}; pool converted once by decode_fns "
        "and streamed whole every step; CPU rows price the dequant "
        "arithmetic — the bandwidth win is the weight_pool_bytes "
        "column (projections shrink ~4x int8 / ~8x int4 under fp32; "
        "embeddings/norms stay model-dtype, and this bench shape's "
        "4096-vocab embedding dominates its tiny pool)")
    rows["weight_quant"] = wq

    # mixed prefill+decode: a continuous-batching window at b=8 where
    # one slot re-admits (prefill) between decode windows — the
    # serving steady state, not a pure-decode best case.  Derived from
    # the loop's already-measured bf16/b=8 cell (a re-run would pay the
    # variant's compile + warmup again for identical numbers).
    ms, pref_ms = mixed_src
    mixed_tps = (8 * STEPS + PROMPT) / (ms * STEPS + pref_ms) * 1e3
    rows["mixed_prefill_decode"] = {
        "decode_ms_per_step": round(ms, 3),
        "prefill_ms": round(pref_ms, 3),
        "tokens_per_sec_per_chip": round(mixed_tps, 1),
        "note": "b=8 bf16: one prompt admission per "
                f"{STEPS}-step decode window",
    }

    # ---- mixed-load rows: long-prompt arrivals against a full batch
    # of already-decoding slots, chunked prefill OFF vs ON vs ON with
    # a shared 512-token prefix (prefix-cache hits).  Measures TTFT
    # p50/p95 of the long arrivals and the decode stall their prefills
    # impose (total + worst single stall while decode slots were
    # live), recorded against the batch-256 cliff above (bf16 tokens/s
    # peaks at b=64 and FALLS at 256) so the next TPU capture
    # quantifies the stall-free win where the cliff lives.  All three
    # variants serve IDENTICAL long prompts (shared 512-token prefix +
    # distinct tails); only the scheduler mode changes.
    from apex_tpu.serving.serve import ContinuousBatcher, Request

    import numpy as np

    MIX_PREFIX, MIX_TAIL, CHUNK = 512, 8, 256
    LONGS, SHORT_NEW, LONG_NEW = 4, 24, 8
    mix_rng = np.random.RandomState(11)
    shared_prefix = mix_rng.randint(1, VOCAB, (MIX_PREFIX,))
    long_prompts = [
        list(map(int, shared_prefix))
        + list(map(int, mix_rng.randint(1, VOCAB, (MIX_TAIL,))))
        for _ in range(LONGS)
    ]
    short_prompts = [list(map(int, mix_rng.randint(1, VOCAB, (8,))))
                     for _ in range(256)]

    def run_mixed(batch, chunked, prefix):
        # the decode STEP's cost is set by the compiled slot width
        # (fixed shapes), not by how many slots are live — so the
        # short-decoder count is capped to keep the CPU row affordable
        # while `batch` still sets the shape whose cliff is measured
        n_short = min(batch, 32) - 1
        long_len = MIX_PREFIX + MIX_TAIL
        pps = -(-(long_len + LONG_NEW) // PAGE)
        num_pages = 1 + n_short * (-(-(8 + SHORT_NEW) // PAGE)) \
            + (LONGS + 2) * pps
        cfg = KVCacheConfig(
            num_layers=LAYERS, num_heads=HEADS,
            head_dim=HIDDEN // HEADS, num_pages=num_pages,
            page_size=PAGE, max_seqs=batch, pages_per_seq=pps,
            dtype=jnp.bfloat16)
        fns = model.decode_fns(
            params, mesh, cfg, max_prompt_len=long_len,
            prefill_chunk=CHUNK if chunked else None)
        batcher = ContinuousBatcher(
            fns.prefill, fns.decode, PagedKVCache(cfg),
            init_pools(cfg), max_prompt_len=long_len, harvest_every=4,
            chunk_fn=fns.chunk,
            prefill_chunk=CHUNK if chunked else None,
            prefix_cache=prefix, measure_stall=True)
        # prime: serve the shared prefix once OUTSIDE the measured
        # window (registers the prefix pages; also pays first-call
        # compiles), then measure the mixed workload where every long
        # arrival can hit
        batcher.run([Request(uid="prime", prompt=long_prompts[0],
                             max_new_tokens=2)])
        batcher.decode_stall_s = 0.0
        batcher.max_prefill_stall_s = 0.0
        for k in batcher.prefix_stats:
            batcher.prefix_stats[k] = 0
        reqs = [Request(uid=f"s{i}", prompt=short_prompts[i],
                        max_new_tokens=SHORT_NEW)
                for i in range(n_short)]
        reqs += [Request(uid=f"L{j}", prompt=long_prompts[j],
                         max_new_tokens=LONG_NEW)
                 for j in range(LONGS)]
        t0 = time.perf_counter()
        comps = batcher.run(reqs)
        wall = time.perf_counter() - t0
        ttfts = sorted(c.ttft_s for uid, c in comps.items()
                       if str(uid).startswith("L"))
        pct = lambda q: ttfts[min(len(ttfts) - 1,
                                  int(round(q * (len(ttfts) - 1))))]
        row = {
            "ttft_p50_ms": round(pct(0.50) * 1e3, 2),
            "ttft_p95_ms": round(pct(0.95) * 1e3, 2),
            "decode_stall_ms": round(batcher.decode_stall_s * 1e3, 2),
            "max_prefill_stall_ms": round(
                batcher.max_prefill_stall_s * 1e3, 2),
            "wall_ms": round(wall * 1e3, 1),
        }
        if chunked:
            row["prefill_chunks"] = batcher.prefill_chunks
        if prefix:
            # rate over the LONG arrivals only: the short decoders'
            # sub-page prompts are structurally unmatchable and would
            # dilute the headline with the short/long mix, not the
            # cache's effectiveness
            px = batcher.prefix_stats
            row["prefix_hit_rate_long_arrivals"] = round(
                px["hits"] / LONGS, 3)
            row["prefill_tokens_skipped"] = px["tokens_skipped"]
            row["pages_shared"] = px["shared_pages"]
        return row

    mixed_load = {}
    for batch in (8, 64, 256):
        per = {}
        for name, chunked, prefix in (
                ("monolithic", False, False),
                ("chunked", True, False),
                ("chunked_prefix", True, True)):
            per[name] = run_mixed(batch, chunked, prefix)
            log(f"mixed b{batch} {name}: "
                f"ttft p95 {per[name]['ttft_p95_ms']} ms, "
                f"max stall {per[name]['max_prefill_stall_ms']} ms")
        per["note"] = (
            f"{min(batch, 32) - 1} short decoders + {LONGS} long "
            f"arrivals ({MIX_PREFIX}-token shared prefix + {MIX_TAIL} "
            "tail) at the batch-wide compiled decode shape; stall = "
            "prefill wall while decode slots were live, queue-drained "
            "before each measurement; prefix primed out-of-window")
        mixed_load[str(batch)] = per
    rows["mixed_load"] = mixed_load

    # ---- speculative decoding rows: n-gram self-speculation (k=4,
    # draft-and-verify through the paged pool) vs the plain one-token
    # step at decode batch {1, 8, 64}, on REPETITIVE prompts (tiled
    # 4-token cycle — the drafter's best case: an untrained model's
    # greedy loop gives the n-gram matcher a periodic context to hit)
    # and ADVERSARIAL prompts (uniform-random tokens — near-zero hits,
    # so the row prices pure verify overhead).  tokens/s is end-to-end
    # through the batcher (prefill + verify + per-step host sync);
    # accepted_tokens_per_step is committed tokens per live slot-step
    # (1.0 = never better than plain).  CPU rows are compute-bound
    # where a TPU decode step is weight-bandwidth-bound, so the on/off
    # ratio here UNDERSTATES the TPU win — informational, not gated.
    from apex_tpu.serving.speculate import NGramDraftSource

    SPEC_K, SPEC_NEW, SPEC_PROMPT = 4, 24, 32
    spec_rng = np.random.RandomState(17)

    def spec_prompts(kind, n):
        out = []
        for _ in range(n):
            if kind == "repetitive":
                pat = spec_rng.randint(1, VOCAB, (4,))
                out.append(list(map(int, np.tile(
                    pat, SPEC_PROMPT // 4)[:SPEC_PROMPT])))
            else:
                out.append(list(map(int, spec_rng.randint(
                    1, VOCAB, (SPEC_PROMPT,)))))
        return out

    def run_spec(batch, spec_on):
        pps = -(-(SPEC_PROMPT + SPEC_NEW) // PAGE)
        cfg = KVCacheConfig(
            num_layers=LAYERS, num_heads=HEADS,
            head_dim=HIDDEN // HEADS, num_pages=1 + batch * pps,
            page_size=PAGE, max_seqs=batch, pages_per_seq=pps,
            dtype=jnp.bfloat16)
        fns = model.decode_fns(
            params, mesh, cfg, max_prompt_len=SPEC_PROMPT,
            speculate_k=SPEC_K if spec_on else None)
        per_kind = {}
        for kind in ("repetitive", "adversarial"):
            kw = {}
            if spec_on:
                kw = dict(spec_fn=fns.spec, speculate_k=SPEC_K,
                          draft_source=NGramDraftSource(SPEC_K))
            batcher = ContinuousBatcher(
                fns.prefill, fns.decode, PagedKVCache(cfg),
                init_pools(cfg), max_prompt_len=SPEC_PROMPT,
                harvest_every=4, **kw)
            prompts = spec_prompts(kind, batch)
            # prime wave pays the first-call compiles out-of-window
            batcher.run([Request(uid="prime", prompt=prompts[0],
                                 max_new_tokens=4)])
            if spec_on:
                for k in list(batcher.spec_stats):
                    batcher.spec_stats[k] = (
                        {} if k == "by_source" else 0)
            reqs = [Request(uid=f"q{i}", prompt=p,
                            max_new_tokens=SPEC_NEW)
                    for i, p in enumerate(prompts)]
            t0 = time.perf_counter()
            comps = batcher.run(reqs)
            wall = time.perf_counter() - t0
            toks = sum(len(c.tokens) for c in comps.values())
            row = {
                "tokens_per_sec": round(toks / wall, 1),
                "wall_ms": round(wall * 1e3, 1),
            }
            if spec_on:
                st = batcher.spec_stats
                row["accepted_tokens_per_step"] = round(
                    st["committed"] / max(st["slot_steps"], 1), 3)
                row["draft_hit_rate"] = round(
                    st["accepted"] / max(st["drafted"], 1), 3)
                row["verify_steps"] = st["steps"]
            per_kind[kind] = row
            log(f"spec b{batch} {'on' if spec_on else 'off'} "
                f"{kind}: {row['tokens_per_sec']:,.0f} tokens/s"
                + (f", {row['accepted_tokens_per_step']} acc/step"
                   if spec_on else ""))
        return per_kind

    speculative = {}
    for batch in (1, 8, 64):
        speculative[str(batch)] = {
            "plain": run_spec(batch, False),
            "speculate_k4": run_spec(batch, True),
        }
    speculative["note"] = (
        f"n-gram self-speculation k={SPEC_K}, {SPEC_NEW} new tokens "
        f"over {SPEC_PROMPT}-token prompts; accepted_tokens_per_step "
        "is committed/slot-step (plain step = 1.0); the untrained "
        "bench weights loop regardless of prompt, so adversarial rows "
        "still draft-hit once the generated tail goes periodic — the "
        "split prices verify overhead, not model-dependent hit rates; "
        "CPU verify is compute-bound so on/off wall ratios understate "
        "the weight-stream win — see docs/serving.md")

    # ---- draft-source crossover cells: the speculation ladder's
    # three real tiers (ngram, model, model ± off-ramp tree) on the
    # ADVERSARIAL prompt set only — repetitive prompts are the n-gram
    # drafter's home turf; the recorded crossover number is
    # accepted_tokens_per_step model vs ngram where prompt-lookup has
    # nothing to hit.  The draft model is THIS model's own int4 pool
    # (shared tokenizer by construction) serving from its own KV
    # slice; draft_wall_frac prices the host-sequential draft loop
    # against the whole serving wall.
    from apex_tpu.serving.speculate import (
        ModelDraftSource, offramp_tree,
    )

    def run_draft_source(source):
        batch = 4
        pps = -(-(SPEC_PROMPT + SPEC_NEW + 2 * SPEC_K) // PAGE)
        cfg = KVCacheConfig(
            num_layers=LAYERS, num_heads=HEADS,
            head_dim=HIDDEN // HEADS, num_pages=1 + batch * pps,
            page_size=PAGE, max_seqs=batch, pages_per_seq=pps,
            dtype=jnp.bfloat16)
        tree = (offramp_tree(SPEC_K) if source == "model_tree"
                else None)
        dm = None
        kw = {}
        if source == "ngram":
            kw = dict(draft_source=NGramDraftSource(SPEC_K))
        else:
            dcfg = KVCacheConfig(
                num_layers=LAYERS, num_heads=HEADS,
                head_dim=HIDDEN // HEADS, num_pages=1 + batch * pps,
                page_size=PAGE, max_seqs=batch, pages_per_seq=pps,
                dtype=jnp.bfloat16)
            dm = ModelDraftSource(
                model, params, mesh, dcfg, k=SPEC_K, tree=tree,
                weight_dtype="int4", weight_block=WQ_BLOCK)
        fns = model.decode_fns(
            params, mesh, cfg, max_prompt_len=SPEC_PROMPT,
            speculate_k=SPEC_K, spec_tree=tree, draft_model=dm)
        batcher = ContinuousBatcher(
            fns.prefill, fns.decode, PagedKVCache(cfg),
            init_pools(cfg), max_prompt_len=SPEC_PROMPT,
            harvest_every=4, spec_fn=fns.spec, speculate_k=SPEC_K,
            **kw)
        prompts = spec_prompts("adversarial", batch)
        batcher.run([Request(uid="prime", prompt=prompts[0],
                             max_new_tokens=4)])
        for k in list(batcher.spec_stats):
            batcher.spec_stats[k] = (
                {} if k == "by_source"
                else 0.0 if k == "draft_s" else 0)
        reqs = [Request(uid=f"q{i}", prompt=p,
                        max_new_tokens=SPEC_NEW)
                for i, p in enumerate(prompts)]
        t0 = time.perf_counter()
        comps = batcher.run(reqs)
        wall = time.perf_counter() - t0
        toks = sum(len(c.tokens) for c in comps.values())
        st = batcher.spec_stats
        row = {
            "tokens_per_sec": round(toks / wall, 1),
            "wall_ms": round(wall * 1e3, 1),
            "accepted_tokens_per_step": round(
                st["committed"] / max(st["slot_steps"], 1), 3),
            "draft_hit_rate": round(
                st["accepted"] / max(st["drafted"], 1), 3),
            "verify_steps": st["steps"],
            "draft_wall_frac": round(
                min(st["draft_s"] / max(wall, 1e-9), 1.0), 3),
        }
        if tree is not None:
            row["offramp_commits"] = st["offramp"]
        log(f"spec source={source} adversarial: "
            f"{row['accepted_tokens_per_step']} acc/slot-step, "
            f"hit {row['draft_hit_rate']}")
        return row

    speculative["draft_source"] = {
        src: run_draft_source(src)
        for src in ("ngram", "model", "model_tree")}
    speculative["draft_source"]["note"] = (
        "adversarial prompts, batch 4: the n-gram-vs-model crossover "
        "as a recorded number; the int4 draft model pays a "
        "host-sequential draft loop (draft_wall_frac) to keep "
        "accepting where lookup misses — on TPU the verify stays "
        "weight-bandwidth-bound so the acceptance gain converts to "
        "wall-clock at scale")
    rows["speculative"] = speculative

    # ---- tensor-parallel rows: the SAME decode step sharded over a
    # tp group (head-sharded KV pool + column/row-split projections,
    # logits gathered only at the sampling seam) at tp {1, 2, 4} x
    # weight {bf16, int8, int4}, one decode batch.  tokens/s/chip
    # divides by tp — on CPU the shard_map partitions fight for the
    # same cores so the wall ratio is pessimistic; the number that
    # transfers is per_chip_weight_pool_bytes (each chip streams 1/tp
    # of the pool, ~1/16th of bf16 at tp=4 x int4 — the weight-stream
    # roofline the tentpole moves).  Block 32 so the int4 per-shard
    # packing divides the tp=4 projection slices (qkv 768 -> 192/chip).
    TP_BLOCK, TP_BATCH = 32, 8
    tp_rows = {}
    for tp in (1, 2, 4):
        parallel_state.destroy_model_parallel()
        tmesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp,
            devices=jax.devices()[:tp])
        per_w = {}
        for weight in ("bf16", "int8", "int4"):
            ms, tps, _, wbytes = run_variant(
                "bfloat16", TP_BATCH, weight=weight, mesh=tmesh,
                wq_block=TP_BLOCK)
            per_w[weight] = {
                "ms_per_step": round(ms, 3),
                "tokens_per_sec_per_chip": round(tps / tp, 1),
                "per_chip_weight_pool_bytes": wbytes,
                "weight_stream_gbs_per_chip": round(
                    wbytes / ms * 1e3 / 1e9, 3),
            }
            log(f"decode tp={tp} w={weight} b{TP_BATCH}: "
                f"{ms:.2f} ms/step, {tps / tp:,.0f} tokens/s/chip, "
                f"{wbytes / 1e6:.2f} MB/chip pool")
        tp_rows[str(tp)] = per_w
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:1])
    tp_rows["note"] = (
        f"b={TP_BATCH}, bf16 KV, weight_block={TP_BLOCK} (int4 "
        "per-shard packing needs the tp=4 projection slice divisible "
        "by 2*block); virtual CPU devices share cores, so ms/step "
        "rises with tp here — read the per-chip pool bytes column; "
        "output is token-identical across tp (pinned in "
        "tests/test_tp_decode.py)")
    rows["tensor_parallel"] = tp_rows

    best = max(v["tokens_per_sec_per_chip"]
               for v in rows["bfloat16"].values())
    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": best,
        "unit": "tokens/s/chip (1 virtual CPU device, bf16 KV)",
        # no TPU measurement happened here: null, not a fake ratio
        # (PR 3 convention)
        "vs_baseline": None,
        "platform": "cpu-virtual",
        "note": "relative cost only — TPU decode rates come from the "
                "next capture's validate_fmha_decode sweep; this row "
                "tracks that the serving stack stays runnable and how "
                "fp32/bf16/int8-KV rank across PRs",
        "batches": rows,
        "spec": {"vocab": VOCAB, "layers": LAYERS, "hidden": HIDDEN,
                 "heads": HEADS, "page_size": PAGE, "prompt": PROMPT,
                 "steps": STEPS, "warmup": WARMUP,
                 "mixed_prefix": MIX_PREFIX, "mixed_tail": MIX_TAIL,
                 "prefill_chunk": CHUNK, "speculate_k": SPEC_K,
                 "spec_prompt": SPEC_PROMPT, "spec_new": SPEC_NEW,
                 "weight_block": WQ_BLOCK, "tp_batch": TP_BATCH,
                 "tp_weight_block": TP_BLOCK},
    }))


def child_fleet():
    """Fleet-tier rows: two continuous-batching replicas behind one
    :class:`~apex_tpu.fleet.FleetRouter`, replaying the deterministic
    bursty shared-prefix trace (``tools/load_gen.py``) under
    prefix-affinity + SLO-priority scheduling vs the round-robin
    baseline, plus the replica-kill drill's ledger.  The headline is
    the interactive p99 TTFT speedup (rr / affinity) on pools sized so
    round-robin thrashes the prefix index — same engineered shape as
    the ``_dryrun_fleet`` gate, but the bench row RECORDS rather than
    asserts.  Always a CPU measurement, so per the PR 3 convention
    ``vs_baseline`` is null."""
    _pin_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.fleet import FleetPolicy, FleetRouter, Replica
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving.kv_cache import (
        KVCacheConfig, PagedKVCache, init_pools,
    )
    from apex_tpu.serving.serve import ContinuousBatcher, Request
    from apex_tpu.transformer import parallel_state
    from tools.load_gen import (
        make_mixed_trace, make_trace, replay, summarize_trace,
    )

    VOCAB, LAYERS, HIDDEN, HEADS = 256, 2, 64, 4
    PAGE, CHUNK, MAXP, PAGES, REPLICAS = 4, 8, 96, 49, 2
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:1])
    model = GPTModel(GPTConfig(
        vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
        num_attention_heads=HEADS, max_position_embeddings=128,
        compute_dtype=jnp.float32, attention_impl="xla", remat=False,
    ))
    params = model.init(jax.random.PRNGKey(0))
    cfg = KVCacheConfig(
        num_layers=LAYERS, num_heads=HEADS, head_dim=HIDDEN // HEADS,
        num_pages=PAGES, page_size=PAGE, max_seqs=2,
        pages_per_seq=-(-MAXP // PAGE), dtype=jnp.float32)
    fns = model.decode_fns(params, mesh, cfg, max_prompt_len=MAXP,
                           prefill_chunk=CHUNK)

    def replicas(n=REPLICAS, offload=None):
        return [
            Replica(f"r{i}", ContinuousBatcher(
                fns.prefill, fns.decode, PagedKVCache(cfg),
                init_pools(cfg), max_prompt_len=MAXP, harvest_every=2,
                chunk_fn=fns.chunk, prefill_chunk=CHUNK,
                prefix_cache=True, offload=offload))
            for i in range(n)
        ]

    # warm every jit outside the measured traces (budget >= 3 covers
    # both decode carry signatures — see _dryrun_fleet)
    rng = np.random.RandomState(15)
    warm = ContinuousBatcher(
        fns.prefill, fns.decode, PagedKVCache(cfg), init_pools(cfg),
        max_prompt_len=MAXP, harvest_every=2, chunk_fn=fns.chunk,
        prefill_chunk=CHUNK, prefix_cache=True)
    warm.run([Request(
        uid="warm", max_new_tokens=4, seed=1,
        prompt=[int(t) for t in rng.randint(1, VOCAB, (88,))])])

    traces = [
        make_trace(n_requests=64, seed=sd, vocab_size=VOCAB,
                   mean_gap=0.5, burstiness=6.0, prompt_len=(68, 88),
                   new_tokens=(4, 8), interactive_frac=0.5, cohorts=4,
                   cohort_frac=0.9, prefix_len=64)
        for sd in (11, 6)
    ]
    rows = {}
    ttfts = {}
    for routing in ("affinity", "least_loaded", "round_robin"):
        pooled = []
        chunks = hits = 0
        t0 = time.perf_counter()
        for trace in traces:
            router = FleetRouter(replicas(),
                                 FleetPolicy(routing=routing))
            recs = replay(router, trace)
            pooled += [r["ttft_s"] for r in recs
                       if r.get("slo") == "interactive"
                       and isinstance(r.get("ttft_s"), (int, float))]
            chunks += sum(r.batcher.prefill_chunks
                          for r in router.replicas)
            hits += sum(r.batcher.prefix_stats["hits"]
                        for r in router.replicas)
        wall = time.perf_counter() - t0
        pooled.sort()
        pct = lambda q: pooled[min(len(pooled) - 1,
                                   int(round(q * (len(pooled) - 1))))]
        ttfts[routing] = pct(0.99)
        rows[routing] = {
            "interactive_ttft_p50_ms": round(pct(0.50) * 1e3, 2),
            "interactive_ttft_p99_ms": round(pct(0.99) * 1e3, 2),
            "prefill_chunks": chunks,
            "prefix_hits": hits,
            "wall_ms": round(wall * 1e3, 1),
        }
        log(f"fleet {routing}: i-p99 "
            f"{rows[routing]['interactive_ttft_p99_ms']} ms, "
            f"{chunks} chunks, {hits} prefix hits")

    # replica-kill drill: r0 dies mid-trace, nothing may be lost
    drill = FleetRouter(replicas(), FleetPolicy())
    drill.replicas[0].fail_after(6)
    dsum = summarize_trace(replay(drill, traces[0]))
    ref = FleetRouter(replicas(), FleetPolicy())
    replay(ref, traces[0])
    identical = all(
        drill.completions[u].tokens == c.tokens
        for u, c in ref.completions.items())
    rows["kill_drill"] = {
        "migrated": drill.stats["migrations"],
        "lost": dsum["lost"],
        "completed": dsum["completed"],
        "token_identical_to_unkilled": identical,
    }
    log(f"fleet drill: {rows['kill_drill']}")

    # disaggregated prefill/decode roles vs unified, same fleet size.
    # The regime where disagg wins BOTH interactive p99 TTFT and ITL:
    # bursty long-prompt arrivals with a real decode budget.  Unified
    # replicas interleave chunked prefills with co-resident decode
    # (stalling ITL) and spread decode across the fleet at batch 1-2;
    # the disagg decode replica gets a role-shaped pool (more slots,
    # same page geometry — compat_key ignores slot counts) so decode
    # consolidates into fewer, larger dispatches, and harvests less
    # often.  Prefill replicas keep harvest_every=2 so finished
    # prefills export promptly.
    from apex_tpu.serving.kv_cache import HostOffloadPool

    pps = -(-MAXP // PAGE)

    def mkcfg(seqs):
        return KVCacheConfig(
            num_layers=LAYERS, num_heads=HEADS,
            head_dim=HIDDEN // HEADS, num_pages=1 + seqs * pps,
            page_size=PAGE, max_seqs=seqs, pages_per_seq=pps,
            dtype=jnp.float32)

    dec_fns = {2: fns}
    for s in (4, 8):
        dec_fns[s] = model.decode_fns(
            params, mesh, mkcfg(s), max_prompt_len=MAXP,
            prefill_chunk=CHUNK)

    def shaped(rid, seqs=2, he=2):
        f, c = dec_fns[seqs], mkcfg(seqs)
        return Replica(rid, ContinuousBatcher(
            f.prefill, f.decode, PagedKVCache(c), init_pools(c),
            max_prompt_len=MAXP, harvest_every=he, chunk_fn=f.chunk,
            prefill_chunk=CHUNK, prefix_cache=True))

    for s in (4, 8):
        shaped("w", seqs=s).batcher.run([Request(
            uid="warm", max_new_tokens=4, seed=1,
            prompt=[int(t) for t in rng.randint(1, VOCAB, (88,))])])

    DEC_HE = 8
    topos = {
        "unified_2r": (lambda: [shaped(f"r{i}") for i in range(2)],
                       None),
        "disagg_2r": (lambda: [shaped("r0"),
                               shaped("r1", seqs=4, he=DEC_HE)],
                      ("prefill", "decode")),
        "unified_4r": (lambda: [shaped(f"r{i}") for i in range(4)],
                       None),
        "disagg_4r": (lambda: [shaped(f"r{i}") for i in range(3)]
                      + [shaped("r3", seqs=8, he=DEC_HE)],
                      ("prefill", "prefill", "prefill", "decode")),
    }
    mixed = make_mixed_trace(
        n_requests=48, seed=21, vocab_size=VOCAB, mean_gap=2.0,
        burstiness=6.0, long_frac=0.6, short_prompt=(8, 16),
        long_prompt=(40, 64), new_tokens=(16, 28), session_frac=0.25,
        idle_gap=16.0)
    pc = lambda xs, q: xs[min(len(xs) - 1,
                              int(round(q * (len(xs) - 1))))]
    med = lambda xs: sorted(xs)[len(xs) // 2]
    # one unmeasured replay per topology warms its handoff/import
    # jits, then 3 INTERLEAVED measured rounds over all topologies —
    # a load spike on the shared CPU then hits every topology in the
    # round, not just whichever happened to be running; the rows are
    # the per-topology medians (token streams are deterministic, only
    # timing varies)
    for build, roles in topos.values():
        replay(FleetRouter(build(), FleetPolicy(roles=roles)), mixed)
    samples = {name: [] for name in topos}
    stats = {}
    for _ in range(3):
        for name, (build, roles) in topos.items():
            t0 = time.perf_counter()
            router = FleetRouter(build(), FleetPolicy(roles=roles))
            recs = replay(router, mixed)
            wall = time.perf_counter() - t0
            stats[name] = router.stats
            inter = [r for r in recs if r.get("slo") == "interactive"
                     and "reason" in r]
            tt = sorted(r["ttft_s"] for r in inter
                        if isinstance(r.get("ttft_s"), (int, float)))
            il = sorted(r["itl_ms"] for r in inter
                        if isinstance(r.get("itl_ms"), (int, float)))
            samples[name].append(
                (pc(tt, .5) * 1e3, pc(tt, .99) * 1e3,
                 pc(il, .5), pc(il, .99), wall * 1e3))
    for name in topos:
        topo, nr = name.split("_")
        reps = samples[name]
        rows[name] = {
            "interactive_ttft_p50_ms": round(med([r[0] for r in reps]), 2),
            "interactive_ttft_p99_ms": round(med([r[1] for r in reps]), 2),
            "interactive_itl_p50_ms": round(med([r[2] for r in reps]), 3),
            "interactive_itl_p99_ms": round(med([r[3] for r in reps]), 3),
            "handoffs": stats[name]["handoffs"],
            "handoff_pages": stats[name]["handoff_pages"],
            "handoff_wire_bytes": stats[name]["handoff_bytes"],
            "wall_ms": round(med([r[4] for r in reps]), 1),
        }
        if topo == "disagg":
            rows[name]["decode_max_seqs"] = 4 if nr == "2r" else 8
            rows[name]["decode_harvest_every"] = DEC_HE
        log(f"fleet {name}: ttft p99 "
            f"{rows[name]['interactive_ttft_p99_ms']} ms, itl p99 "
            f"{rows[name]['interactive_itl_p99_ms']} ms, "
            f"{stats[name]['handoffs']} handoffs")

    # host-RAM offload tier: a prefix working set sized 2x ONE
    # replica's pool, revisited after churn evicted it — fault-in
    # (offload) vs full prefill recompute (none)
    rng_ws = np.random.RandomState(23)
    ws = [[int(t) for t in rng_ws.randint(1, VOCAB, (32,))]
          for _ in range(2 * (PAGES - 1) // (32 // PAGE))]
    for mode in ("offload", "recompute"):
        off = (HostOffloadPool(max_pages=4 * (PAGES - 1))
               if mode == "offload" else None)
        b = replicas(n=1, offload=off)[0].batcher

        def wave(tag):
            c0 = b.prefill_chunks
            t0 = time.perf_counter()
            for i, p in enumerate(ws):
                b.run([Request(uid=f"{tag}{i}", prompt=p,
                               max_new_tokens=4, seed=31 + i)])
            return (round((time.perf_counter() - t0) * 1e3, 1),
                    b.prefill_chunks - c0)
        w1_ms, w1_chunks = wave("w1_")
        w2_ms, w2_chunks = wave("w2_")
        rows[f"offload_{mode}"] = {
            "working_set_pages": len(ws) * (32 // PAGE),
            "replica_pool_pages": PAGES - 1,
            "wave1_ms": w1_ms, "wave1_prefill_chunks": w1_chunks,
            "wave2_ms": w2_ms, "wave2_prefill_chunks": w2_chunks,
        }
        if off is not None:
            rows["offload_offload"].update({
                "pages_offloaded": off.stats["offloaded"],
                "pages_faulted": off.stats["faulted"],
                "host_bytes_peak": off.stats["bytes_in"],
            })
        log(f"offload {mode}: wave2 {w2_ms} ms, "
            f"{w2_chunks} prefill chunks")

    speedup = ttfts["round_robin"] / ttfts["affinity"]
    print(json.dumps({
        "metric": "fleet_interactive_p99_ttft_speedup",
        "value": round(speedup, 2),
        "unit": "x (round_robin / affinity+SLO, 2 replicas, "
                "2 pooled 64-request traces)",
        # no TPU measurement happened here: null, not a fake ratio
        # (PR 3 convention)
        "vs_baseline": None,
        "platform": "cpu-virtual",
        "note": "scheduling-quality row — pools sized so round-robin "
                "thrashes the prefix index (4 cohorts, ~2 fit); "
                "records the routing win and the zero-loss drill, "
                "asserted by the _dryrun_fleet gate",
        "rows": rows,
        "spec": {"vocab": VOCAB, "layers": LAYERS, "hidden": HIDDEN,
                 "heads": HEADS, "page_size": PAGE,
                 "prefill_chunk": CHUNK, "num_pages": PAGES,
                 "replicas": REPLICAS, "max_prompt_len": MAXP,
                 "trace_seeds": [11, 6], "requests_per_trace": 64,
                 "mixed_trace_seed": 21, "mixed_requests": 48,
                 "disagg_decode_harvest_every": 8},
    }))


def child_telemetry():
    """Telemetry-overhead row: ms/step of the flagship CPU-dryrun-shape
    GPT step (the same reduced config child_gpt's CPU fallback
    measures) with runtime metrics ON (MetricsLogger at the default
    flush cadence, JSONL sink) vs OFF, plus the logger's self-measured
    overhead split into bookkeeping tax vs amortized resolve wait.
    Always a CPU measurement, so per the PR 3 convention
    ``vs_baseline`` is null — the row tracks that async harvesting
    stays effectively free across PRs, not a TPU win."""
    import tempfile

    _pin_cpu()
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.telemetry.metrics import MetricsLogger, StepStats
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.tensor_parallel.layers import state_specs_like
    from apex_tpu._compat import shard_map

    # the flagship CPU-dryrun shape (child_gpt's fallback config)
    VOCAB, LAYERS, HIDDEN, HEADS, SEQ, BATCH = 4096, 2, 256, 4, 256, 2
    WARMUP, STEPS, REPEATS = 2, 10, 3
    mesh = parallel_state.initialize_model_parallel()
    cfg = GPTConfig(
        vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
        num_attention_heads=HEADS, max_position_embeddings=SEQ,
        compute_dtype=jnp.bfloat16, attention_impl="xla", remat=True,
    )
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    specs = model.param_specs()
    opt = FusedAdam(lr=1e-4, master_weights=True)
    opt_state = opt.init(params)
    opt_specs = state_specs_like(specs, opt_state)

    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss)(
            params, tokens, targets)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        new_params, new_opt = opt.step(opt_state, grads, params)
        return new_params, new_opt, loss

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(specs, opt_specs, P("dp"), P("dp")),
        out_specs=(specs, opt_specs, P()),
    ))
    place = lambda tree, sp: jax.device_put(
        tree, jax.tree.map(lambda s: NamedSharding(mesh, s), sp,
                           is_leaf=lambda x: isinstance(x, P)))
    params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (BATCH, SEQ),
                                0, VOCAB)
    targets = jnp.roll(tokens, -1, axis=1)

    def run_once(with_metrics):
        p = place(params, specs)
        s = place(opt_state, opt_specs)
        tlm = None
        if with_metrics:
            tlm = MetricsLogger(
                jsonl_path=os.path.join(tempfile.mkdtemp(), "m.jsonl"),
                console=False, flush_every=10,
                stats=StepStats(tokens_per_step=BATCH * SEQ,
                                peak_flops=None),
            )
        for _ in range(WARMUP):
            p, s, loss = step(p, s, tokens, targets)
        float(loss)
        t0 = time.perf_counter()
        for i in range(STEPS):
            p, s, loss = step(p, s, tokens, targets)
            if tlm is not None:
                if i == 0:
                    tlm.stats.begin(loss)
                else:
                    tlm.stats.tick()
                tlm.log_scalars(i, loss=loss)
        if tlm is not None:
            tlm.close()
        else:
            float(loss)
        dt = time.perf_counter() - t0
        return dt / STEPS * 1e3, tlm

    off_ms = min(run_once(False)[0] for _ in range(REPEATS))
    on_runs = [run_once(True) for _ in range(REPEATS)]
    on_ms = min(ms for ms, _ in on_runs)
    tlm = min(on_runs, key=lambda r: r[0])[1]
    overhead_pct = round(
        tlm.overhead_s / STEPS / (on_ms / 1e3) * 100, 4)
    resolve_pct = round(
        tlm.resolve_wait_s / STEPS / (on_ms / 1e3) * 100, 4)
    log(f"telemetry: off {off_ms:.2f} ms/step, on {on_ms:.2f} ms/step, "
        f"self-measured tax {overhead_pct}% (+{resolve_pct}% resolve "
        "wait)")
    print(json.dumps({
        "metric": "telemetry_overhead_pct",
        # headline = the logger's self-measured bookkeeping tax as a
        # fraction of step time (stable); the on-vs-off A/B rides along
        # but min-of-3 wall clocks on a shared CPU host carry ±% noise
        "value": overhead_pct,
        "unit": "% of step time",
        "vs_baseline": None,
        "platform": "cpu",
        "note": "flagship CPU-dryrun shape; vs_baseline null per the "
                "PR 3 CPU convention — the <1% gate runs in the "
                "multichip dryrun's telemetry config",
        "ms_per_step_metrics_off": round(off_ms, 3),
        "ms_per_step_metrics_on": round(on_ms, 3),
        "resolve_wait_pct": resolve_pct,
        "flush_every": 10,
        "spec": {"vocab": VOCAB, "layers": LAYERS, "hidden": HIDDEN,
                 "heads": HEADS, "seq": SEQ, "batch": BATCH,
                 "steps": STEPS, "warmup": WARMUP,
                 "repeats": REPEATS},
    }))


def child_opttail():
    """Optimizer-tail A/B row: ms/step of the fused multi-tensor tail
    (``FusedAdam(fused_tail=True).step_scaled`` — unscale + finiteness
    + Adam + master→bf16 cast in ONE pass over packed buffers) vs the
    seed per-leaf chain (``scaler.unscale`` pass + per-leaf ``upd``),
    on a flagship-layout GPT param tree scaled to the CPU dryrun
    budget.  Always a CPU measurement, so per the PR 3 convention
    ``vs_baseline`` is null — the real bandwidth gate is
    ``tools/kernel_validation.py validate_opt_tail`` on the next TPU
    capture (PROFILE_r05's 11.85 ms / 440 GB/s tail baseline); this
    row tracks that both paths stay runnable, their relative cost, and
    that fused-vs-per-leaf outputs stay BIT-identical."""
    _pin_cpu()
    import numpy as np
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp.scaler import all_finite, scale_gradients
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.fused_tail import (
        tail_traffic_bytes,
        time_opt_tail,
    )

    LAYERS, HIDDEN, VOCAB = 2, 256, 4096  # child_gpt's CPU shape
    ks = jax.random.split(jax.random.PRNGKey(0), LAYERS + 2)
    params = {"emb": 0.02 * jax.random.normal(
        ks[0], (VOCAB, HIDDEN), jnp.bfloat16)}
    for l in range(LAYERS):
        params[f"l{l}"] = {
            "qkv": 0.02 * jax.random.normal(
                ks[l + 1], (HIDDEN, 3 * HIDDEN), jnp.bfloat16),
            "mlp": 0.02 * jax.random.normal(
                ks[l + 1], (HIDDEN, 4 * HIDDEN), jnp.bfloat16),
            "ln": jnp.ones((HIDDEN,), jnp.bfloat16),
        }
    grads = jax.tree.map(
        lambda p: 0.01 * jax.random.normal(
            jax.random.PRNGKey(9), jnp.shape(p),
            jnp.float32).astype(p.dtype),
        params)
    inv = 1.0 / 1024.0

    fused = FusedAdam(lr=1e-3, master_weights=True, fused_tail=True)
    perleaf = FusedAdam(lr=1e-3, master_weights=True)
    f_state, p_state = fused.init(params), perleaf.init(params)

    # parity before timing: the fused tail's contract is bit-identity
    fp, fs, _ = jax.jit(
        lambda s, g, p: fused.step_scaled(s, g, p, jnp.float32(inv))
    )(f_state, grads, params)
    rg = scale_gradients(grads, inv)
    rp, rs = jax.jit(
        lambda s, g, p, f: perleaf.step(s, g, p, grads_finite=f)
    )(p_state, rg, params, all_finite(grads))
    for a, b in zip(jax.tree.leaves(fp), jax.tree.leaves(rp)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    f = time_opt_tail(fused, f_state, grads, params, inv_scale=inv,
                      iters=10)

    def seed_chain(s, g, p):
        g2 = scale_gradients(g, inv)
        finite = all_finite(g)
        return perleaf.step(s, g2, p, grads_finite=finite)

    jseed = jax.jit(seed_chain)
    out = jseed(p_state, grads, params)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(10):
        out = jseed(p_state, grads, params)
    jax.block_until_ready(out)
    seed_ms = (time.perf_counter() - t0) / 10 * 1e3
    n_elems = sum(int(np.prod(jnp.shape(l)))
                  for l in jax.tree.leaves(params))
    log(f"opt tail: fused {f['ms']:.2f} ms vs per-leaf "
        f"{seed_ms:.2f} ms ({n_elems / 1e6:.1f}M params)")
    print(json.dumps({
        "metric": "opt_tail_ms_per_step",
        "value": round(f["ms"], 3),
        "unit": "ms (fused tail, CPU)",
        "vs_baseline": None,
        "platform": "cpu",
        "note": "CPU-dryrun-scale tail; vs_baseline null per the PR 3 "
                "convention — the bandwidth gate is kernel_validation "
                "validate_opt_tail on TPU (11.85 ms r05 baseline). "
                "fused_vs_per_leaf < 1 HERE is the CPU backend's "
                "unfused concatenate (the bucket pack is a real copy "
                "on CPU; TPU fuses concats into the consumer loop)",
        "fused_ms": round(f["ms"], 3),
        "per_leaf_ms": round(seed_ms, 3),
        "fused_vs_per_leaf": round(seed_ms / max(f["ms"], 1e-9), 2),
        "traffic_bytes": tail_traffic_bytes(params, fused),
        "cpu_gbs": round(f["gbs"], 2),
        "bit_identical": True,
        "spec": {"layers": LAYERS, "hidden": HIDDEN, "vocab": VOCAB,
                 "elements": n_elems, "steps": 10, "warmup": 2,
                 "unscale_folded": True},
    }))


def _flash_long_seq(out, on_tpu, timeit):
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.attention import flash_attention

    S_long = 8192 if on_tpu else 512
    bq, hq, dq = (2, 8, 128) if on_tpu else (1, 2, 32)
    qkv_keys = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(qkv_keys[0], (bq, hq, S_long, dq), jnp.bfloat16)
    k = jax.random.normal(qkv_keys[1], (bq, hq, S_long, dq), jnp.bfloat16)
    v = jax.random.normal(qkv_keys[2], (bq, hq, S_long, dq), jnp.bfloat16)
    fa_grad = jax.jit(jax.grad(
        lambda q, k, v: flash_attention(
            q, k, v, causal=True
        ).astype(jnp.float32).sum(),
        argnums=(0, 1, 2),
    ))
    out["flash_long_seq"] = {
        "seq": S_long, "shape": [bq, hq, S_long, dq], "dtype": "bfloat16",
        "causal": True,
        "fwd_bwd_ms": round(timeit(fa_grad, q, k, v, n=10), 2),
    }
    log(f"flash s={S_long}: {out['flash_long_seq']['fwd_bwd_ms']:.2f} ms fwd+bwd")


def _t5_extra(out, on_tpu):
    # T5 encoder-decoder train step (enc-dec model family on the record;
    # sequential tp=1 path on the single chip)
    import time

    import jax
    import jax.numpy as jnp

    from apex_tpu.models import T5Config, T5Model
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.tensor_parallel.layers import state_specs_like
    from jax.sharding import NamedSharding, PartitionSpec as P

    t5_cfg = T5Config(
        vocab_size=32768 if on_tpu else 256,
        num_encoder_layers=6 if on_tpu else 1,
        num_decoder_layers=6 if on_tpu else 1,
        hidden_size=512 if on_tpu else 64,
        num_attention_heads=8 if on_tpu else 2,
        max_position_embeddings=512,
        compute_dtype=jnp.bfloat16,
    )
    t5_s = 512 if on_tpu else 32
    t5_b = 16 if on_tpu else 2
    t5 = T5Model(t5_cfg)
    t5_params = t5.init(jax.random.PRNGKey(7))
    t5_specs = t5.param_specs()
    t5_opt = FusedAdam(lr=1e-4, master_weights=True)
    t5_opt_state = t5_opt.init(t5_params)
    t5_opt_specs = state_specs_like(t5_specs, t5_opt_state)
    t5_mesh = parallel_state.initialize_model_parallel() \
        if not parallel_state.model_parallel_is_initialized() \
        else parallel_state.get_mesh()

    def t5_step(params, opt_state, enc, dec, tgt):
        loss, grads = jax.value_and_grad(t5.loss)(params, enc, dec, tgt)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        new_params, new_opt = t5_opt.step(opt_state, grads, params)
        return new_params, new_opt, loss

    t5_fn = jax.jit(
        jax.shard_map(
            t5_step, mesh=t5_mesh,
            in_specs=(t5_specs, t5_opt_specs, P("dp"), P("dp"), P("dp")),
            out_specs=(t5_specs, t5_opt_specs, P()),
        ),
        donate_argnums=(0, 1),
    )
    t5_place = lambda tree, sp: jax.device_put(
        tree, jax.tree.map(lambda s_: NamedSharding(t5_mesh, s_), sp,
                           is_leaf=lambda x_: isinstance(x_, P)))
    t5_params = jax.tree.map(lambda p_: p_.astype(jnp.bfloat16), t5_params)
    tp_, ts_ = t5_place(t5_params, t5_specs), t5_place(t5_opt_state, t5_opt_specs)
    t5_enc = jax.random.randint(
        jax.random.PRNGKey(8), (t5_b, t5_s), 0, t5_cfg.vocab_size)
    t5_dec = jax.random.randint(
        jax.random.PRNGKey(9), (t5_b, t5_s), 0, t5_cfg.vocab_size)
    t5_tgt = jax.random.randint(
        jax.random.PRNGKey(10), (t5_b, t5_s), 0, t5_cfg.vocab_size)
    for _ in range(2):
        tp_, ts_, t5_loss = t5_fn(tp_, ts_, t5_enc, t5_dec, t5_tgt)
    float(t5_loss)
    t5_steps = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(t5_steps):
        tp_, ts_, t5_loss = t5_fn(tp_, ts_, t5_enc, t5_dec, t5_tgt)
    t5_final = float(t5_loss)
    dt = time.perf_counter() - t0
    out["t5_encdec"] = {
        # decoder tokens/s (the enc side adds 6 more bidirectional layers
        # of work per step on the same count)
        "tokens_per_sec": round(t5_b * t5_s * t5_steps / dt, 1),
        "ms_per_step": round(dt / t5_steps * 1e3, 2),
        "loss": round(t5_final, 4),
        "spec": {"enc_layers": t5_cfg.num_encoder_layers,
                 "dec_layers": t5_cfg.num_decoder_layers,
                 "hidden": t5_cfg.hidden_size, "seq": t5_s,
                 "batch": t5_b, "steps": t5_steps, "warmup": 2,
                 "compute_dtype": "bfloat16",
                 "optimizer": "FusedAdam(master_weights=True)"},
    }
    log(f"t5: {out['t5_encdec']['tokens_per_sec']} dec tokens/s "
        f"({out['t5_encdec']['ms_per_step']} ms/step)")


# ---------------------------------------------------------------- orchestrator
def _merge_bench_extra(path, extras):
    """Merge this run's extras into BENCH_EXTRA.json instead of
    clobbering it: a budget-starved run that only produced (say) the
    fleet row must not erase the grad-sync/zero3/decode rows a fuller
    earlier capture wrote.  This run's keys win on collision (they are
    fresher measurements of the same thing); unknown or unreadable
    existing content is replaced, not merged."""
    merged = dict(extras)
    try:
        with open(path) as f:
            prior = json.load(f)
        if isinstance(prior, dict):
            merged = {**prior, **extras}
    except (OSError, ValueError):
        pass
    try:
        with open(path, "w") as f:
            json.dump(merged, f, indent=1)
    except OSError as e:
        log(f"extras write failed: {e}")


def _run_child(args, timeout):
    """Run `python bench.py <args>` bounded; return (ok, last_json, tail).

    Timeout handling is SIGTERM-first with a long grace period, NEVER an
    immediate SIGKILL: a child holding the TPU claim that dies without
    client teardown wedges the axon pool's single-chip grant for >1h
    (round-3 post-mortem).  SIGTERM hits the child's clean-exit handler
    (`_install_sigterm_exit`); SIGKILL only after the grace expires.
    """
    env = dict(os.environ)
    # persistent XLA-executable cache: a gate-time bench re-running the
    # same flagship program should pay tracing, not compilation — the
    # r4 extras child died to a cold 20-40s-per-program compile backlog.
    # TPU children only: cached CPU AOT executables warn about host
    # machine-feature mismatches ("could lead to SIGILL"), and CPU
    # compiles are cheap anyway.
    if "cpu" not in args:
        env.setdefault(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"),
        )
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + args,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
    )
    timed_out = False
    try:
        out, errtxt = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.terminate()  # SIGTERM -> child's clean-exit handler
        try:
            out, errtxt = proc.communicate(timeout=45)
        except subprocess.TimeoutExpired:
            log("child ignored SIGTERM for 45s; escalating to SIGKILL "
                "(chip claim may wedge)")
            proc.kill()
            out, errtxt = proc.communicate()
    sys.stderr.write((errtxt or "")[-4000:])
    if timed_out or proc.returncode != 0:
        # salvage: children emit cumulative partial JSON at section
        # boundaries, so a timeout mid-compile keeps completed sections
        for line in reversed((out or "").strip().splitlines()):
            try:
                partial = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(partial, dict):
                reason = (f"timeout after {timeout}s" if timed_out
                          else f"rc={proc.returncode}")
                if partial.get("partial"):
                    partial["truncated_by"] = reason
                    log(f"child died ({reason}) but left a partial "
                        "result; keeping it")
                else:
                    # complete result printed, then a messy teardown
                    log(f"child died in teardown ({reason}) after a "
                        "complete result; keeping it")
                return True, partial, ""
            break
        if timed_out:
            return False, None, f"timeout after {timeout}s"
        return False, None, (errtxt or "")[-1500:]
    for line in reversed((out or "").strip().splitlines()):
        try:
            return True, json.loads(line), ""
        except json.JSONDecodeError:
            continue
    return False, None, "no JSON in child output"


def _clear_tpu_watcher():
    """Gate-time right-of-way: if tools/tpu_watch.py is mid-probe, its
    queued claim would contend with this bench's.  SIGTERM it — its
    handler tears down the probe child FIRST (tools/tpu_watch.py
    _sigterm), releasing the lane cleanly — and wait for the lock to
    drop before probing ourselves."""
    lock = "/tmp/apex_tpu_watch.lock"
    try:
        pid = int(open(lock).read().strip())
    except (OSError, ValueError):
        return
    if pid == os.getppid():
        # this bench IS the watcher's capture child: killing the parent
        # would terminate ourselves (its child-first teardown targets
        # exactly this process) — the lane is already ours
        return
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().decode("utf-8", "replace")
    except OSError:
        cmdline = ""
    if "tpu_watch" not in cmdline:
        # stale lock whose pid was recycled by an unrelated process:
        # never signal it, just clear the husk
        try:
            os.remove(lock)
        except OSError:
            pass
        return
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        try:
            os.remove(lock)
        except OSError:
            pass
        return
    log(f"waiting for tpu_watch (pid {pid}) to release the chip lane")
    # the watcher's teardown waits up to ~300s for a claim-holding
    # probe to exit cleanly; give it that long plus slack
    for _ in range(420):
        if not os.path.exists(lock):
            log("tpu_watch released")
            return
        time.sleep(1)
    log("tpu_watch did not release within 420s; proceeding anyway")


def main():
    t_start = time.perf_counter()
    errors = []
    _clear_tpu_watcher()

    def budget_left():
        return TOTAL_BUDGET - (time.perf_counter() - t_start)

    # Probe with exponential backoff until the probe budget OR the
    # measurement reserve runs out, whichever comes first.  The r5
    # lesson cuts both ways: the axon chip-claim wedge outlives any
    # fixed small retry count (so backoff, not N retries) — but probing
    # all the way down to the reserve burned 1500 s of the r05 gate
    # before the CPU fallback started, so the loop now has its own cap
    # (APEX_TPU_BENCH_PROBE_BUDGET) and a same-boot failure cache that
    # skips the probe entirely when a recent run already paid for the
    # same answer.
    platform = None
    backoff = 20
    attempt = 0
    # the reserve can never eat the whole budget: at least one probe
    # attempt always runs (a small-budget env var combo must not turn
    # the gate into a silent CPU bench) — unless probing is skipped
    # outright by budget 0 or the failure cache
    reserve = min(MEASURE_RESERVE, max(0, TOTAL_BUDGET - PROBE_TIMEOUT - 60))
    cached = _cached_probe_failure()
    if PROBE_BUDGET <= 0:
        errors.append("probe skipped: APEX_TPU_BENCH_PROBE_BUDGET <= 0")
        log(errors[-1])
    elif cached is not None:
        errors.append(
            "probe skipped: same-boot failure cached in BENCH_WATCH.json "
            f"({time.time() - cached.get('at', 0):.0f}s ago, "
            f"{cached.get('attempts', '?')} attempts); set "
            "APEX_TPU_BENCH_PROBE_CACHE_S=0 to force a probe")
        log(errors[-1])
    else:
        probe_t0 = time.perf_counter()

        def probe_left():
            return PROBE_BUDGET - (time.perf_counter() - probe_t0)

        while attempt == 0 or (budget_left() > reserve
                               and probe_left() > 0):
            ok, probe, err = _run_child(
                ["--child", "probe"],
                min(PROBE_TIMEOUT, max(30, budget_left() - reserve),
                    max(30, probe_left())),
            )
            if ok:
                platform = probe["platform"]
                log(f"probe: {probe}")
                break
            tail = err.strip().splitlines()[-1] if err.strip() else err
            errors.append(f"probe[{attempt}]: {tail}")
            log(f"probe attempt {attempt} failed: {err[-300:]}")
            attempt += 1
            sleep_for = min(backoff, max(0, budget_left() - reserve),
                            max(0, probe_left()))
            if sleep_for <= 0:
                break
            log(f"probe backoff: sleeping {sleep_for:.0f}s "
                f"({budget_left():.0f}s budget, "
                f"{probe_left():.0f}s probe budget left)")
            time.sleep(sleep_for)
            backoff = min(backoff * 2, 600)
    if platform is None and attempt > 0:
        # real gave-up (not a deliberate skip, which already logged its
        # own reason above): record it so the next same-boot run skips
        errors.append(
            f"probe gave up after {attempt} attempts / "
            f"{time.perf_counter() - t_start:.0f}s "
            f"(reserve {reserve}s, probe budget {PROBE_BUDGET}s)")
        boot = _boot_id()
        if boot is not None:
            _set_probe_failure({"boot_id": boot, "at": time.time(),
                                "attempts": attempt})
    elif platform is not None and platform != "cpu":
        # chip contact invalidates any cached failure immediately
        _set_probe_failure(None)

    result = None
    on_tpu = False
    if platform is not None and platform != "cpu":
        for retry in range(2):
            # the probe loop may have spent down to the reserve: clamp
            # the measurement child to the remaining budget so probe +
            # 2 children + sleep can never overrun TOTAL_BUDGET
            child_budget = min(CHILD_TIMEOUT, max(0, budget_left()))
            if child_budget < 120:
                errors.append(
                    f"tpu-gpt[{retry}]: skipped, only "
                    f"{child_budget:.0f}s budget left")
                break
            ok, result, err = _run_child(
                ["--child", "gpt", "--platform", platform], child_budget
            )
            if ok:
                on_tpu = True
                break
            errors.append(f"tpu-gpt[{retry}]: {err[-300:]}")
            result = None
            if retry == 0 and budget_left() > 150:
                time.sleep(30)

    if result is None:
        # TPU unreachable or measurement failed: CPU fallback so the
        # bench still emits a valid, clearly-marked measurement.  Clamp
        # to the remaining budget with a 300s floor (the CPU child at
        # the fallback config finishes well inside it) so this leg
        # cannot extend a fully-spent gate window by CHILD_TIMEOUT
        ok, result, err = _run_child(
            ["--child", "gpt", "--platform", "cpu"],
            min(CHILD_TIMEOUT, max(300, budget_left())),
        )
        if not ok:
            errors.append(f"cpu-gpt: {err[-300:]}")
            result = {
                "metric": "gpt_tp1_tokens_per_sec",
                "value": 0.0,
                "unit": "tokens/s",
                # no measurement happened: null, not a fake ratio
                "vs_baseline": None,
                "error": "; ".join(errors)[-800:],
            }
            last = _load_last_tpu()
            if last:
                result["last_tpu_result"] = last
            print(json.dumps(result))
            return

    # extra BASELINE.md targets — never allowed to break the main metric
    extras = None
    if budget_left() <= 300:
        log(f"skipping extras: only {budget_left():.0f}s of total budget left")
    else:
        ok, extras, err = _run_child(
            ["--child", "extras", "--platform", result.get("platform", "cpu")],
            min(budget_left(), CHILD_TIMEOUT),
        )
        if not ok:
            extras = None
            log(f"extras failed (non-fatal): {err[-300:]}")
        else:
            log(f"extras: {extras}")

    # grad-sync A/B row (overlap x compression on the virtual
    # hierarchical mesh) — rides BENCH_EXTRA.json, never the headline
    if budget_left() > 180:
        ok, gs, err = _run_child(
            ["--child", "gradsync", "--platform", "cpu"],
            min(budget_left(), 600),
        )
        if ok:
            extras = extras if extras is not None else {
                "platform": "cpu-virtual"}
            extras["grad_sync"] = gs
            log(f"grad_sync: {gs}")
        else:
            log(f"grad-sync row failed (non-fatal): {err[-300:]}")
    else:
        log(f"skipping grad-sync row: {budget_left():.0f}s budget left")

    # optimizer-tail A/B row (fused multi-tensor pass vs the seed
    # per-leaf chain) — rides BENCH_EXTRA.json, never the headline
    if budget_left() > 150:
        ok, ot, err = _run_child(
            ["--child", "opttail", "--platform", "cpu"],
            min(budget_left(), 600),
        )
        if ok:
            extras = extras if extras is not None else {
                "platform": "cpu-virtual"}
            extras["opt_tail"] = ot
            log(f"opt_tail: {ot}")
        else:
            log(f"opt-tail row failed (non-fatal): {err[-300:]}")
    else:
        log(f"skipping opt-tail row: {budget_left():.0f}s budget left")

    # ZeRO-3 A/B row (gather-on-use sharded step vs replicated at the
    # dryrun shape) — rides BENCH_EXTRA.json, never the headline
    if budget_left() > 150:
        ok, z3, err = _run_child(
            ["--child", "zero3", "--platform", "cpu"],
            min(budget_left(), 600),
        )
        if ok:
            extras = extras if extras is not None else {
                "platform": "cpu-virtual"}
            extras["zero3"] = z3
            log(f"zero3: {z3}")
        else:
            log(f"zero3 row failed (non-fatal): {err[-300:]}")
    else:
        log(f"skipping zero3 row: {budget_left():.0f}s budget left")

    # telemetry-overhead row (metrics on vs off at the flagship
    # CPU-dryrun shape) — rides BENCH_EXTRA.json, never the headline
    if budget_left() > 150:
        ok, tl, err = _run_child(
            ["--child", "telemetry", "--platform", "cpu"],
            min(budget_left(), 600),
        )
        if ok:
            extras = extras if extras is not None else {
                "platform": "cpu-virtual"}
            extras["telemetry_overhead"] = tl
            log(f"telemetry_overhead: {tl}")
        else:
            log(f"telemetry row failed (non-fatal): {err[-300:]}")
    else:
        log(f"skipping telemetry row: {budget_left():.0f}s budget left")

    # decode-throughput rows (the serving stack's tokens/s/chip at
    # batch {1,8,64,256} + mixed prefill+decode) — rides
    # BENCH_EXTRA.json, never the headline
    if budget_left() > 150:
        ok, dc, err = _run_child(
            ["--child", "decode", "--platform", "cpu"],
            min(budget_left(), 600),
        )
        if ok:
            extras = extras if extras is not None else {
                "platform": "cpu-virtual"}
            extras["decode"] = dc
            log(f"decode: {dc}")
        else:
            log(f"decode row failed (non-fatal): {err[-300:]}")
    else:
        log(f"skipping decode row: {budget_left():.0f}s budget left")

    # fleet-tier row (multi-replica routing + failover drill over the
    # serving stack) — rides BENCH_EXTRA.json, never the headline
    if budget_left() > 150:
        ok, fl, err = _run_child(
            ["--child", "fleet", "--platform", "cpu"],
            min(budget_left(), 600),
        )
        if ok:
            extras = extras if extras is not None else {
                "platform": "cpu-virtual"}
            extras["fleet"] = fl
            log(f"fleet: {fl}")
        else:
            log(f"fleet row failed (non-fatal): {err[-300:]}")
    else:
        log(f"skipping fleet row: {budget_left():.0f}s budget left")

    if extras is not None:
        _merge_bench_extra(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_EXTRA.json"),
            extras)

    if on_tpu:
        # only real-TPU extras may become "last TPU" hardware
        # evidence: the grad-sync fallback dict is tagged
        # "cpu-virtual" and must not clobber previously captured
        # TPU extras (which _save_last_tpu otherwise carries forward)
        ex_platform = str((extras or {}).get("platform", ""))
        _save_last_tpu(result,
                       extras if extras is not None
                       and not ex_platform.startswith("cpu") else None)
    else:
        # hardware evidence survives a flaky tunnel: attach the last
        # TPU-captured record (timestamp + git sha) to the fallback
        last = _load_last_tpu()
        if last:
            result["last_tpu_result"] = last
    if errors:
        prior = result.get("note", "")
        result["note"] = (prior + "; " if prior else "") + "; ".join(errors)[-500:]
    print(json.dumps(result))


if __name__ == "__main__":
    if "--child" in sys.argv:
        _install_sigterm_exit()
        kind = sys.argv[sys.argv.index("--child") + 1]
        plat = (
            sys.argv[sys.argv.index("--platform") + 1]
            if "--platform" in sys.argv else "cpu"
        )
        if kind == "probe":
            child_probe()
        elif kind == "gpt":
            child_gpt(plat)
        elif kind == "extras":
            child_extras(plat)
        elif kind == "gradsync":
            child_gradsync()
        elif kind == "zero3":
            child_zero3()
        elif kind == "opttail":
            child_opttail()
        elif kind == "telemetry":
            child_telemetry()
        elif kind == "decode":
            child_decode()
        elif kind == "fleet":
            child_fleet()
        else:
            raise SystemExit(f"unknown child {kind}")
    else:
        main()
